"""Prometheus text exposition (v0.0.4) — render and parse.

``render`` turns a :class:`~predictionio_tpu.obs.metrics.MetricsRegistry`
into the ``GET /metrics`` body every server exposes; ``parse_text`` is
the inverse used by ``pio top`` and ``loadgen --scrape-metrics`` to read
a fleet's exposition back without a client dependency. Only the subset
this repo emits is supported: ``# HELP``/``# TYPE`` comments, counter/
gauge samples, and histogram ``_bucket``/``_sum``/``_count`` series.

Format reference: the Prometheus exposition-formats spec. The
non-obvious rules honored here:

- label values escape ``\\``, ``"`` and newline;
- histogram buckets are *cumulative* and always end with ``le="+Inf"``;
- sample lines for one metric family are contiguous under its ``# TYPE``.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Tuple

from .metrics import Histogram, MetricsRegistry

__all__ = ["CONTENT_TYPE", "render", "parse_text"]

#: ``respond()`` appends "; charset=UTF-8" itself
CONTENT_TYPE = "text/plain; version=0.0.4"


def _escape_label(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _fmt_labels(names: Tuple[str, ...], values: Tuple[str, ...]) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


def _fmt_value(value: float) -> str:
    # NaN/±Inf first: int(nan) raises and int(-inf) overflows, and a
    # single bad gauge value must never take down every later scrape
    if math.isnan(value):
        return "NaN"
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _fmt_le(bound: float) -> str:
    return "+Inf" if math.isinf(bound) else _fmt_value(bound)


def render(registry: MetricsRegistry) -> str:
    """The full ``GET /metrics`` body, trailing newline included."""
    lines: List[str] = []
    for inst in registry.collect():
        lines.append(f"# HELP {inst.name} {inst.help}")
        lines.append(f"# TYPE {inst.name} {inst.kind}")
        if isinstance(inst, Histogram):
            for key, _child in inst.series():
                labels = dict(zip(inst.labelnames, key))
                snap = inst.snapshot(**labels)
                for bound, cum in snap["buckets"]:
                    blabels = _fmt_labels(
                        inst.labelnames + ("le",), key + (_fmt_le(bound),)
                    )
                    lines.append(f"{inst.name}_bucket{blabels} {cum}")
                base = _fmt_labels(inst.labelnames, key)
                lines.append(
                    f"{inst.name}_sum{base} {_fmt_value(snap['sum'])}"
                )
                lines.append(f"{inst.name}_count{base} {snap['count']}")
        else:
            for key, child in inst.series():
                base = _fmt_labels(inst.labelnames, key)
                lines.append(
                    f"{inst.name}{base} {_fmt_value(child.value)}"
                )
    return "\n".join(lines) + "\n"


# -- parsing (pio top / loadgen --scrape-metrics) ---------------------------

#: the label body is quote-aware: a '}' INSIDE a quoted label value
#: (legal, unescaped per spec) must not terminate the group early
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r'(?:\{(?P<labels>(?:[^"{}]|"(?:[^"\\]|\\.)*")*)\})?'
    r"\s+(?P<value>\S+)\s*$"
)
_LABEL_PAIR_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"'
)


_UNESCAPE_RE = re.compile(r"\\(.)")
_UNESCAPES = {"n": "\n", '"': '"', "\\": "\\"}


def _unescape_label(value: str) -> str:
    # single pass, not chained str.replace: 'a\\nb' (escaped backslash
    # before a literal n) must not have its '\\n' re-read as a newline
    return _UNESCAPE_RE.sub(
        lambda m: _UNESCAPES.get(m.group(1), m.group(0)), value
    )


def _parse_value(text: str) -> float:
    if text == "+Inf":
        return math.inf
    if text == "-Inf":
        return -math.inf
    return float(text)


def parse_text(text: str) -> Dict[str, List[Tuple[Dict[str, str], float]]]:
    """Exposition text → ``{sample_name: [(labels, value), ...]}``.

    Histogram families appear under their sample names (``x_bucket``,
    ``x_sum``, ``x_count``) — the shape scraping code actually wants.
    Unparseable lines are skipped (a scraper must survive a newer peer).
    """
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        labels: Dict[str, str] = {}
        if m.group("labels"):
            for lm in _LABEL_PAIR_RE.finditer(m.group("labels")):
                labels[lm.group(1)] = _unescape_label(lm.group(2))
        try:
            value = _parse_value(m.group("value"))
        except ValueError:
            continue
        out.setdefault(m.group("name"), []).append((labels, value))
    return out
