"""Observability plane: metrics registry, Prometheus exposition, tracing.

The fleet-visibility subsystem (``docs/observability.md``): every server
process owns a :class:`MetricsRegistry` (counters / gauges / log-scale
histograms with bounded label cardinality) exposed as Prometheus text on
``GET /metrics``, and a :class:`Tracer` recording ``X-PIO-Trace``-keyed
spans into a ring buffer dumped via ``GET /traces.json``. ``pio top``
scrapes a node list into one fleet table; ``pio trace <id>`` stitches a
single request's spans across processes.

Stdlib-only and device-free, like ``utils/resilience.py`` — importable
from every server and client path.

Performance observability (ISSUE 8) rides on top: ``obs/profile.py``
(jit compile/retrace telemetry, :class:`PhaseProfiler` device-fenced
phase timings with roofline estimates) and ``obs/perfledger.py`` (the
durable perf ledger behind ``pio perf diff|trend``).
"""

from .metrics import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    OVERFLOW_VALUE,
    percentile_from_buckets,
)
from .trace import (
    TRACE_HEADER,
    SpanContext,
    SpanStore,
    Tracer,
    current_context,
    new_trace_id,
)
from .expo import CONTENT_TYPE as PROMETHEUS_CONTENT_TYPE
from .expo import parse_text, render
from .profile import (
    JitTelemetry,
    PhaseProfiler,
    default_telemetry,
    profiling_enabled,
    render_profile_report,
    roofline,
)
from .flight import FlightRecorder, StallWatchdog, default_recorder
from .slo import (
    HealthConfig,
    HealthPlane,
    SLOEngine,
    SLOObjective,
    default_objectives,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "DEFAULT_BUCKETS",
    "OVERFLOW_VALUE",
    "percentile_from_buckets",
    "TRACE_HEADER",
    "SpanContext",
    "SpanStore",
    "Tracer",
    "current_context",
    "new_trace_id",
    "PROMETHEUS_CONTENT_TYPE",
    "render",
    "parse_text",
    "JitTelemetry",
    "PhaseProfiler",
    "default_telemetry",
    "profiling_enabled",
    "render_profile_report",
    "roofline",
    "FlightRecorder",
    "StallWatchdog",
    "default_recorder",
    "HealthConfig",
    "HealthPlane",
    "SLOEngine",
    "SLOObjective",
    "default_objectives",
]
