"""Cross-process request tracing: ``X-PIO-Trace`` ids + in-process spans.

One online request touches three processes (query server → storage
server → replica) plus background delivery threads; when its tail
latency spikes, per-server histograms say *that* it was slow, not
*where*. A trace answers where:

- the client (or the first server to see the request) mints a **trace
  id** and sends it in the ``X-PIO-Trace`` header;
- every server creates a **server span** at admission carrying that id,
  and every instrumented stage inside the process (micro-batch queue
  wait, device dispatch, remote storage calls, feedback delivery) adds
  child spans;
- outbound calls (``storage/remote.py``, feedback POSTs) forward the
  header, so the downstream server's spans join the same trace;
- each process keeps its spans in a bounded in-memory ring buffer
  (:class:`SpanStore`) dumped via ``GET /traces.json``; ``pio trace
  <id>`` stitches the dumps from a node list back into one timeline.

This is deliberately *not* a distributed tracer with collectors and
sampling — it is the smallest thing that makes a single slow request
explainable across the fleet (the profiling-hooks-first philosophy of
the training side, ``utils/profiling.py``, applied to serving).

Ambient propagation mirrors ``utils/resilience.deadline_scope``: a
contextvar carries the live request's :class:`SpanContext` so deep call
sites (the remote storage client under an engine's ``supplement``) pick
it up without signature changes. Contextvars do not cross threads —
work handed to another thread (MicroBatcher workers, the feedback pool)
must capture :func:`current_context` at submit time and pass it
explicitly (``Tracer.span(..., parent=ctx)``).

Clocks are injectable (``Tracer(clock=..., wall=...)``): every trace
test runs with zero wall-clock sleeps.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import secrets
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterator, List, Optional

__all__ = [
    "TRACE_HEADER",
    "SpanContext",
    "SpanStore",
    "Tracer",
    "current_context",
    "new_trace_id",
]

#: Wire header carrying the trace id. Value contract: an opaque token of
#: 1-64 URL-safe characters; anything longer/weirder is truncated and
#: sanitized at admission (a garbled header must degrade, never 500).
TRACE_HEADER = "X-PIO-Trace"

_MAX_ID_LEN = 64
_ID_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_."
)


def new_trace_id() -> str:
    """16 hex chars — unique enough for a per-fleet debugging session."""
    return secrets.token_hex(8)


def sanitize_trace_id(value: Optional[str]) -> Optional[str]:
    """Header value → usable trace id, or None when absent/empty."""
    if not value:
        return None
    cleaned = "".join(c for c in value.strip() if c in _ID_OK)[:_MAX_ID_LEN]
    return cleaned or None


@dataclasses.dataclass(frozen=True)
class SpanContext:
    """What a child span (possibly on another thread) needs of its
    parent: the ids and the tracer whose store it records into."""

    trace_id: str
    span_id: str
    tracer: "Tracer"


_ambient_span: contextvars.ContextVar = contextvars.ContextVar(
    "pio_span", default=None
)


def current_context() -> Optional[SpanContext]:
    """The span context of the request this thread is serving, if any."""
    return _ambient_span.get()


class SpanStore:
    """Bounded ring buffer of finished spans (newest win; a busy server
    forgets old traces instead of growing without bound)."""

    def __init__(self, capacity: int = 2048):
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=capacity)

    def add(self, span: dict) -> None:
        with self._lock:
            self._spans.append(span)

    def dump(self) -> List[dict]:
        with self._lock:
            return list(self._spans)

    def for_trace(self, trace_id: str) -> List[dict]:
        return [s for s in self.dump() if s.get("traceId") == trace_id]

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


class Tracer:
    """Per-process (per-server) span factory bound to one store.

    ``clock`` measures durations (monotonic); ``wall`` stamps span start
    times (epoch seconds) so cross-process dumps sort into one timeline.
    Both injectable for sleep-free tests.
    """

    def __init__(
        self,
        service: str,
        store: Optional[SpanStore] = None,
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
    ):
        self.service = service
        self.store = store if store is not None else SpanStore()
        self.clock = clock
        self.wall = wall

    # -- span creation ----------------------------------------------------
    @contextlib.contextmanager
    def server_span(
        self,
        name: str,
        header_value: Optional[str] = None,
        tags: Optional[Dict[str, object]] = None,
    ) -> Iterator[SpanContext]:
        """The admission span: joins the trace named by an incoming
        ``X-PIO-Trace`` header, or roots a fresh one. Sets the ambient
        context for the request's dynamic extent."""
        trace_id = sanitize_trace_id(header_value) or new_trace_id()
        yield from self._run_span(name, trace_id, None, tags, kind="server")

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        tags: Optional[Dict[str, object]] = None,
        parent: Optional[SpanContext] = None,
    ) -> Iterator[SpanContext]:
        """A child of ``parent`` (default: the ambient context; with
        neither, roots a fresh trace). Use an explicit ``parent`` when
        crossing threads — the ambient contextvar does not follow."""
        parent = parent if parent is not None else current_context()
        trace_id = parent.trace_id if parent else new_trace_id()
        parent_id = parent.span_id if parent else None
        yield from self._run_span(name, trace_id, parent_id, tags)

    def _run_span(self, name, trace_id, parent_id, tags, kind="internal"):
        ctx = SpanContext(trace_id, secrets.token_hex(4), self)
        token = _ambient_span.set(ctx)
        start_wall = self.wall()
        t0 = self.clock()
        error: Optional[str] = None
        try:
            yield ctx
        except BaseException as exc:
            error = type(exc).__name__
            raise
        finally:
            _ambient_span.reset(token)
            self.record(
                name=name,
                ctx=ctx,
                parent_id=parent_id,
                start_wall=start_wall,
                duration_s=self.clock() - t0,
                tags=tags,
                kind=kind,
                error=error,
            )

    def record(
        self,
        name: str,
        ctx: SpanContext,
        parent_id: Optional[str],
        start_wall: float,
        duration_s: float,
        tags: Optional[Dict[str, object]] = None,
        kind: str = "internal",
        error: Optional[str] = None,
    ) -> None:
        """Append one finished span (also the entry point for callers
        that measured timing themselves, e.g. the MicroBatcher's
        queue-wait span whose start predates the dispatch thread)."""
        span = {
            "traceId": ctx.trace_id,
            "spanId": ctx.span_id,
            "parentId": parent_id,
            "service": self.service,
            "kind": kind,
            "name": name,
            "startMs": round(start_wall * 1000.0, 3),
            "durationMs": round(max(0.0, duration_s) * 1000.0, 3),
        }
        if tags:
            span["tags"] = {k: v for k, v in tags.items()}
        if error:
            span["error"] = error
        self.store.add(span)

    def child_context(self, parent: Optional[SpanContext]) -> SpanContext:
        """A pre-minted context for a span whose lifetime is managed by
        hand (cross-thread timing); pair with :meth:`record`."""
        trace_id = parent.trace_id if parent else new_trace_id()
        return SpanContext(trace_id, secrets.token_hex(4), self)
