"""Thread-safe metrics registry: counters, gauges, log-scale histograms.

The observability plane's data model (``docs/observability.md``). Every
server process owns one :class:`MetricsRegistry`; instruments are
created idempotently by name, carry a fixed *label-name* schema, and
accept label *values* per observation. The design constraints, in order:

- **stdlib-only and device-free** — like ``utils/resilience.py``, this
  must import from the Event Server and storage client paths where jax
  may not exist.
- **bounded cardinality** — a label set is a time series the scraper
  must store forever; a label value interpolated from request data
  (user ids, query strings) grows without bound and takes the whole
  metrics plane down with it. The registry enforces a hard per-metric
  cap (``max_label_sets``): past it, new label sets collapse into one
  ``{label="_overflow"}`` series — the explosion is *visible* (the
  overflow series grows) instead of fatal. The ``obs-unbounded-label``
  lint rule catches the bug class at AST level before it ships.
- **injectable clocks** — nothing here reads a wall clock except
  through the constructor-supplied callable, so histogram/ gauge tests
  run with zero wall-clock sleeps (the ISSUE-2 discipline).
- **fixed log-scale histogram buckets** — tail latency spans four
  orders of magnitude between a warm cache hit and a cold XLA compile;
  power-of-two buckets give constant relative error across the whole
  range at a fixed, mergeable series count (the Prometheus model, not
  a quantile sketch: scrapers can sum bucket counters across a fleet).

Exposition lives in :mod:`predictionio_tpu.obs.expo`; this module knows
nothing about wire formats.
"""

from __future__ import annotations

import math
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "OVERFLOW_VALUE",
    "DEFAULT_BUCKETS",
    "percentile_from_buckets",
]

#: the label value every over-cap label set collapses into
OVERFLOW_VALUE = "_overflow"

#: Default histogram buckets (seconds): powers of two from 0.5 ms to
#: ~65 s. 18 buckets cover a sub-millisecond cache hit and a cold-start
#: XLA compile in the same instrument at ~2x relative error.
DEFAULT_BUCKETS: Tuple[float, ...] = tuple(
    0.0005 * (2.0 ** i) for i in range(18)
)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def percentile_from_buckets(
    uppers: Sequence[float], cumulative: Sequence[int], q: float
) -> float:
    """Estimate the ``q`` (0..1) percentile from cumulative bucket counts
    (Prometheus ``histogram_quantile`` semantics: linear interpolation
    inside the first bucket whose cumulative count reaches rank).

    ``uppers`` are the finite upper bounds; ``cumulative[i]`` counts
    observations ``<= uppers[i]``; a final element of ``cumulative`` one
    longer than ``uppers`` is the +Inf (total) count. Returns 0.0 with
    no observations; observations beyond the last finite bound clamp to
    it (the estimate cannot exceed what the buckets can resolve)."""
    total = cumulative[-1] if cumulative else 0
    if total <= 0:
        return 0.0
    rank = q * total
    prev_bound = 0.0
    prev_count = 0
    for upper, count in zip(uppers, cumulative):
        if count >= rank:
            in_bucket = count - prev_count
            if in_bucket <= 0 or math.isinf(upper):
                return prev_bound
            frac = (rank - prev_count) / in_bucket
            return prev_bound + (upper - prev_bound) * frac
        prev_bound, prev_count = upper, count
    return uppers[-1] if uppers else 0.0


class _Instrument:
    """Base: child series keyed by label-value tuples, under one lock."""

    kind = ""

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str],
        max_label_sets: int,
    ):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r} on {name}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._max_label_sets = max_label_sets
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            # the unlabelled series exists from creation (a counter that
            # never fired still exposes 0 — absence is ambiguous)
            self._children[()] = self._new_child()

    def _new_child(self):
        raise NotImplementedError

    def _key(self, labels: Dict[str, object]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}"
            )
        return tuple(str(labels[ln]) for ln in self.labelnames)

    def _child(self, labels: Dict[str, object]):
        """Get-or-create the series for one label-value set, applying the
        cardinality bound (caller does NOT hold the lock)."""
        key = self._key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if (
                    self.labelnames
                    and len(self._children) >= self._max_label_sets
                ):
                    # collapse, don't drop: the overflow series keeps the
                    # totals honest and its growth IS the alarm
                    key = tuple(OVERFLOW_VALUE for _ in self.labelnames)
                    child = self._children.get(key)
                    if child is None:
                        child = self._new_child()
                        self._children[key] = child
                else:
                    child = self._new_child()
                    self._children[key] = child
            return child

    def series(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())

    def _labels_of(self, key: Tuple[str, ...]) -> Dict[str, str]:
        return dict(zip(self.labelnames, key))

    def clear(self) -> None:
        """Drop every series — for re-exported state whose label sets
        can change (a ``/reload`` swapping the deployed instance must
        not leave the old instance's series behind). The unlabelled
        series is re-created at zero."""
        with self._lock:
            self._children.clear()
            if not self.labelnames:
                self._children[()] = self._new_child()


class _CounterChild:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0


class Counter(_Instrument):
    """Monotonically increasing count."""

    kind = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up")
        child = self._child(labels)
        with self._lock:
            child.value += amount

    def value(self, **labels) -> float:
        child = self._child(labels)
        with self._lock:
            return child.value

    def samples(self) -> List[Tuple[Dict[str, str], float]]:
        """Every series as ``(labels, value)`` — the in-process twin of
        a scraped exposition (the SLO engine reads counters this way)."""
        with self._lock:
            return [
                (self._labels_of(key), child.value)
                for key, child in sorted(self._children.items())
            ]


class _GaugeChild:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0


class Gauge(_Instrument):
    """Point-in-time value; may also be backed by a collect-time callback
    (:meth:`MetricsRegistry.gauge_callback`)."""

    kind = "gauge"

    def _new_child(self):
        return _GaugeChild()

    def set(self, value: float, **labels) -> None:
        child = self._child(labels)
        with self._lock:
            child.value = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        child = self._child(labels)
        with self._lock:
            child.value += amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        child = self._child(labels)
        with self._lock:
            return child.value

    def samples(self) -> List[Tuple[Dict[str, str], float]]:
        """Every series as ``(labels, value)`` (see Counter.samples)."""
        with self._lock:
            return [
                (self._labels_of(key), child.value)
                for key, child in sorted(self._children.items())
            ]


class _HistogramChild:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)  # +1 = the +Inf bucket
        self.sum = 0.0
        self.count = 0


class Histogram(_Instrument):
    """Fixed-bucket histogram (cumulative exposition, per-bucket storage).

    ``buckets`` are the finite upper bounds, strictly increasing; the
    +Inf bucket is implicit. Defaults to the log-scale
    :data:`DEFAULT_BUCKETS`."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str],
        max_label_sets: int,
        buckets: Optional[Sequence[float]] = None,
    ):
        bounds = tuple(buckets) if buckets is not None else DEFAULT_BUCKETS
        if not bounds or any(
            b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])
        ):
            raise ValueError(
                f"{name}: buckets must be non-empty and strictly increasing"
            )
        self.buckets = bounds
        super().__init__(name, help, labelnames, max_label_sets)

    def _new_child(self):
        return _HistogramChild(len(self.buckets))

    def observe(self, value: float, **labels) -> None:
        child = self._child(labels)
        # bisect over a ~18-entry tuple: the linear scan is cache-friendly
        # and the upper bound is fixed, so no log-vs-linear cliff
        idx = len(self.buckets)
        for i, bound in enumerate(self.buckets):
            if value <= bound:
                idx = i
                break
        with self._lock:
            child.counts[idx] += 1
            child.sum += value
            child.count += 1

    def snapshot(self, **labels) -> Dict[str, object]:
        """Cumulative view of one series: ``{"buckets": [(le, n), ...],
        "sum": s, "count": n}`` (the exposition shape, pre-format)."""
        child = self._child(labels)
        with self._lock:
            counts = list(child.counts)
            total_sum, total = child.sum, child.count
        cumulative = []
        running = 0
        for bound, n in zip(self.buckets, counts[:-1]):
            running += n
            cumulative.append((bound, running))
        cumulative.append((math.inf, total))
        return {"buckets": cumulative, "sum": total_sum, "count": total}

    def percentile(self, q: float, **labels) -> float:
        snap = self.snapshot(**labels)
        uppers = [b for b, _ in snap["buckets"]]
        cums = [n for _, n in snap["buckets"]]
        return percentile_from_buckets(uppers, cums, q)

    def label_snapshots(
        self,
    ) -> List[Tuple[Dict[str, str], Dict[str, object]]]:
        """Every series as ``(labels, snapshot)`` — the cumulative shape
        of :meth:`snapshot` per label set, so the SLO engine can count
        under-threshold observations across the whole family."""
        with self._lock:
            raw = [
                (self._labels_of(key), list(child.counts), child.sum,
                 child.count)
                for key, child in sorted(self._children.items())
            ]
        out: List[Tuple[Dict[str, str], Dict[str, object]]] = []
        for labels, counts, total_sum, total in raw:
            cumulative = []
            running = 0
            for bound, n in zip(self.buckets, counts[:-1]):
                running += n
                cumulative.append((bound, running))
            cumulative.append((math.inf, total))
            out.append(
                (labels,
                 {"buckets": cumulative, "sum": total_sum, "count": total})
            )
        return out


class MetricsRegistry:
    """One process/server's instrument set.

    Instruments are created idempotently: ``counter(name)`` twice
    returns the same object; a name re-used with a different kind or
    label schema raises (silent divergence would corrupt exposition).
    """

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        max_label_sets: int = 64,
    ):
        self.clock = clock
        self.max_label_sets = max_label_sets
        self._lock = threading.Lock()
        self._instruments: Dict[str, _Instrument] = {}
        self._callbacks: List[Tuple[Gauge, Dict[str, str], Callable]] = []

    def _get_or_create(self, cls, name, help, labelnames, **kwargs):
        with self._lock:
            existing = self._instruments.get(name)
            if existing is not None:
                if type(existing) is not cls or existing.labelnames != tuple(
                    labelnames
                ):
                    raise ValueError(
                        f"metric {name!r} re-registered with a different "
                        "kind or label schema"
                    )
                # bucket bounds are schema too: a second site observing
                # against different bounds would silently land in +Inf
                want = kwargs.get("buckets")
                if want is not None and tuple(want) != existing.buckets:
                    raise ValueError(
                        f"histogram {name!r} re-registered with different "
                        "buckets"
                    )
                return existing
            inst = cls(name, help, labelnames, self.max_label_sets, **kwargs)
            self._instruments[name] = inst
            return inst

    def counter(
        self, name: str, help: str = "", labelnames: Sequence[str] = ()
    ) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(
        self, name: str, help: str = "", labelnames: Sequence[str] = ()
    ) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        buckets: Optional[Sequence[float]] = None,
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def instrument(self, name: str) -> Optional[_Instrument]:
        """The registered instrument of that name, or None — the
        in-process read path the SLO engine evaluates objectives over
        (absence is the abstention signal, never an error)."""
        with self._lock:
            return self._instruments.get(name)

    def gauge_callback(
        self,
        name: str,
        fn: Callable[[], float],
        help: str = "",
        labels: Optional[Dict[str, str]] = None,
    ) -> Gauge:
        """A gauge whose value is *pulled* at collect time — the zero-
        maintenance way to export existing state (breaker states, queue
        depths, replication lag) without littering set() calls through
        the owning code. ``fn`` must be cheap and non-blocking; a raise
        freezes the series at its last value (a broken callback must not
        take down ``/metrics``)."""
        labels = dict(labels or {})
        gauge = self.gauge(name, help=help, labelnames=sorted(labels))
        with self._lock:
            self._callbacks.append((gauge, labels, fn))
        return gauge

    def collect(self) -> List[_Instrument]:
        """All instruments, callback gauges refreshed, stable name order."""
        with self._lock:
            callbacks = list(self._callbacks)
            instruments = sorted(self._instruments.items())
        for gauge, labels, fn in callbacks:
            try:
                gauge.set(float(fn()), **labels)
            except Exception:
                pass  # last value stands; exposition must never 500
        return [inst for _, inst in instruments]
