"""Durable performance ledger + regression gates (``pio perf``).

BENCH went five rounds without moving and nothing noticed, because each
round's number lived in its own ``BENCH_r0N.json`` and no tool ever put
two of them side by side. The ledger is the fix (the TensorFlow/ads-
infrastructure papers' "regression tracking is load-bearing
infrastructure" discipline, PAPERS.md):

- every ``bench.py`` run (``BENCH_LEDGER=path``) and training run
  (``PIO_PERF_LEDGER=path``) appends ONE schema-versioned JSON line —
  value, device, scale, lever flags, RMSE, phases — to an append-only
  JSONL file;
- ``pio perf diff`` loads the ledger plus the checked-in
  ``BENCH_r0*.json`` history, groups records that are honestly
  comparable (same metric, device class, scale and lever flags — a CPU
  fallback number must never gate a TPU number), and flags any latest
  value that is worse than the median of its predecessors beyond a
  noise band; exit 1 is the CI regression signal;
- ``pio perf trend`` renders the full trajectory so the kernel arc
  (sort-gather, fused gather, bf16) has a history it is accountable to.

Records are dicts, the file is line-delimited JSON, corrupt lines are
skipped on load (an append torn by a crash must not eat the history),
and appends fsync — the ledger is evidence, not a cache.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Dict, List, Optional, Tuple

__all__ = [
    "SCHEMA_VERSION",
    "alert_records",
    "append_record",
    "bench_to_record",
    "cache_records",
    "ckpt_records",
    "comparable_key",
    "detect_regressions",
    "find_no_prior",
    "fleet_records",
    "ingest_records",
    "load_bench_history",
    "load_ledger",
    "make_record",
    "migration_records",
    "quality_records",
    "quant_records",
    "render_trend",
    "shared_cache_records",
    "sharded_records",
]

SCHEMA_VERSION = 1

#: env naming the ledger file training runs append to (bench.py has its
#: own ``BENCH_LEDGER`` knob so the revalidation queue opts in without
#: touching the stdout contract)
LEDGER_ENV = "PIO_PERF_LEDGER"

#: Flag a latest value this much worse than the median of its
#: predecessors. The checked-in CPU-fallback history wobbles ~10%
#: run-to-run on a contended host (BENCH_r02–r05: 12.36–13.71 s), so
#: the default band sits above that noise and below the 20% injected-
#: regression bar the tier-1 self-test drives.
DEFAULT_NOISE_BAND = 0.15

#: comparisons need at least this many predecessor records — one prior
#: point is an anecdote, not a baseline
MIN_HISTORY = 2

_BENCH_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def make_record(
    source: str,
    metric: str,
    value: float,
    unit: str = "s",
    device: Optional[str] = None,
    scale: Optional[float] = None,
    levers: Optional[Dict[str, object]] = None,
    rmse: Optional[float] = None,
    vs_baseline: Optional[float] = None,
    phases: Optional[Dict[str, float]] = None,
    extra: Optional[dict] = None,
    recorded_at: Optional[float] = None,
) -> dict:
    """One schema-versioned ledger record. ``unit == "s"`` and
    ``unit == "bytes"`` mean lower is better (the only units the
    regression gate compares); everything else is trend-only."""
    record: dict = {
        "schema": SCHEMA_VERSION,
        "source": source,
        "metric": metric,
        "value": float(value),
        "unit": unit,
    }
    if recorded_at is not None:
        record["recorded_at_unix"] = float(recorded_at)
    if device is not None:
        record["device"] = device
    if scale is not None:
        record["scale"] = scale
    if levers:
        record["levers"] = dict(levers)
    if rmse is not None:
        record["rmse"] = rmse
    if vs_baseline is not None:
        record["vs_baseline"] = vs_baseline
    if phases:
        record["phases"] = dict(phases)
    if extra:
        record["extra"] = dict(extra)
    return record


def bench_to_record(bench: dict, source: str = "bench") -> dict:
    """Normalize one ``bench.py`` stdout record into the ledger schema.
    Lever flags travel under ``levers`` so :func:`comparable_key` has a
    single place to read them from, old and new records alike."""
    return make_record(
        source=source,
        metric=str(bench.get("metric", "unknown")),
        value=float(bench.get("value", -1.0)),
        unit=str(bench.get("unit", "s")),
        device=bench.get("device"),
        scale=bench.get("scale"),
        levers={
            "solve_mode": bench.get("solve_mode", "auto"),
            "gather_dtype": bench.get("gather_dtype", "f32"),
            "sort_gather": bool(bench.get("sort_gather", False)),
            "fused_gather": bool(bench.get("fused_gather", False)),
            "fallback": bench.get("fallback", ""),
        },
        rmse=bench.get("holdout_rmse"),
        vs_baseline=bench.get("vs_baseline"),
        phases=bench.get("bucketize_stage_phases_s"),
        extra={
            key: bench[key]
            for key in (
                "iterations", "nnz", "error", "jit", "servingFleet",
                "quality", "bf16_gate", "ingestScaling", "cachedFleet",
                "shardedTrain", "migrationDrill", "sharedCache",
                "quantServe", "ckptResume",
            )
            if key in bench
        },
    )


def fleet_records(bench: dict, source: str = "bench") -> List[dict]:
    """The serving-fleet numbers a bench run attached
    (``bench["servingFleet"]``, from ``loadgen --replicas`` —
    docs/fleet.md) as their own ledger records, so serving scale gates
    alongside train time:

    - ``fleet_served_p50_s`` — seconds, lower-better → gated by
      ``pio perf diff`` at a per-record 0.25 band: the median of the
      drive is statistically stable, but it is still wall-clock from an
      in-process fleet sharing a possibly-contended CI box (the same
      reason the jax-cache compile-ratio assertion was retired), so the
      bar sits above scheduler weather and below a real 1.3×+ slowdown;
    - ``fleet_served_p99_s`` — seconds, lower-better, gated at a WIDER
      band (0.5): the p99 of a ~100-request in-process drive is one
      scheduler hiccup away from 2×, so only a serving collapse (an
      accidental sleep, a lock convoy) should fire the gate, not
      CI-box weather;
    - ``fleet_served_qps`` — higher-better, so it rides as a trend-only
      record (the gate only ever compares ``unit == "s"``).

    The replica count travels as ``scale``: a 3-replica run must never
    gate a 2-replica run. A failed fleet drive (``ok`` false) records
    nothing — its latencies measured a broken fleet, not the code."""
    fleet = bench.get("servingFleet")
    if not isinstance(fleet, dict) or not fleet.get("ok"):
        return []
    out: List[dict] = []
    # sharded drives are a different workload (scatter/gather to every
    # backend per query) — their latency must never gate a replicated
    # drive's, so the fleet shape lives in the METRIC NAME, like the
    # replica count lives in scale
    prefix = (
        "fleet_sharded_served" if fleet.get("sharded") else "fleet_served"
    )
    for key, metric, band in (
        ("servedP50Ms", f"{prefix}_p50_s", 0.25),
        ("servedP99Ms", f"{prefix}_p99_s", 0.5),
    ):
        value_ms = fleet.get(key)
        if isinstance(value_ms, (int, float)) and value_ms > 0:
            record = make_record(
                source=source,
                metric=metric,
                value=float(value_ms) / 1000.0,
                unit="s",
                device=bench.get("device"),
                scale=fleet.get("replicas"),
                extra={"sharded": bool(fleet.get("sharded"))},
            )
            record["noise_band"] = band
            out.append(record)
    qps = fleet.get("servedQPS")
    if isinstance(qps, (int, float)) and qps > 0:
        out.append(
            make_record(
                source=source,
                metric=f"{prefix}_qps",
                value=float(qps),
                unit="qps",
                device=bench.get("device"),
                scale=fleet.get("replicas"),
                extra={"sharded": bool(fleet.get("sharded"))},
            )
        )
    return out


def cache_records(bench: dict, source: str = "bench") -> List[dict]:
    """The serve-from-memory numbers a bench run attached
    (``bench["cachedFleet"]``, from ``loadgen --cached-hot-set`` —
    docs/fleet.md#cache) as their own ledger records:

    - ``fleet_cached_p99_s`` — seconds through the cache-on router on
      the Zipfian hot-set mix, lower-better → GATED, at the same wide
      record-declared band (0.5) as the fleet p99: the tail of a small
      in-process drive is one scheduler hiccup from 2×, so only a cache
      collapse (a lock convoy, an accidental always-miss) should fire;
    - ``fleet_cached_qps`` — the step-function headline, higher-better →
      trend-only (the gate only compares ``unit == "s"``); the uncached
      twin QPS and the speedup travel in ``extra`` so the trend renders
      the step, not just the number;
    - ``fleet_cache_hit_rate`` — trend-only ``ratio`` (the drill itself
      hard-gates correctness: byte identity and zero stale responses).

    A failed drive (``ok`` false) records nothing — its numbers measured
    a broken cache, not the code."""
    cached = bench.get("cachedFleet")
    if not isinstance(cached, dict) or not cached.get("ok"):
        return []
    out: List[dict] = []
    p99_ms = cached.get("cachedP99Ms")
    if isinstance(p99_ms, (int, float)) and p99_ms > 0:
        record = make_record(
            source=source,
            metric="fleet_cached_p99_s",
            value=float(p99_ms) / 1000.0,
            unit="s",
            device=bench.get("device"),
            scale=cached.get("replicas"),
            extra={"hitRate": cached.get("hitRate")},
        )
        record["noise_band"] = 0.5
        out.append(record)
    qps = cached.get("cachedQPS")
    if isinstance(qps, (int, float)) and qps > 0:
        out.append(
            make_record(
                source=source,
                metric="fleet_cached_qps",
                value=float(qps),
                unit="qps",
                device=bench.get("device"),
                scale=cached.get("replicas"),
                extra={
                    "uncachedQPS": cached.get("uncachedQPS"),
                    "speedup": cached.get("speedup"),
                    "hitRate": cached.get("hitRate"),
                },
            )
        )
    hit_rate = cached.get("hitRate")
    if isinstance(hit_rate, (int, float)):
        out.append(
            make_record(
                source=source,
                metric="fleet_cache_hit_rate",
                value=float(hit_rate),
                unit="ratio",
                device=bench.get("device"),
                scale=cached.get("replicas"),
            )
        )
    return out


def shared_cache_records(bench: dict, source: str = "bench") -> List[dict]:
    """The shared-tier numbers a bench run attached
    (``bench["sharedCache"]``, from ``loadgen --shared-cache-drill`` —
    docs/fleet.md#shared-cache-tier) as their own ledger records:

    - ``fleet_hedged_p99_s`` — seconds through the hedged router on the
      healthy (tier-up) phase of the drill, lower-better → GATED at the
      same wide record-declared band (0.5) as the other in-process
      serving tails: one scheduler hiccup doubles a small drive's p99,
      so only a real collapse (hedging gone wrong, a tier that blocks
      the request path) should fire;
    - ``fleet_shared_hit_rate`` — trend-only ``ratio`` (the drill
      itself hard-gates correctness: zero stale responses, byte
      identity across the kill, every degrade recorded).

    A failed drill (``ok`` false) records nothing — its numbers
    measured a broken tier, not the code."""
    shared = bench.get("sharedCache")
    if not isinstance(shared, dict) or not shared.get("ok"):
        return []
    out: List[dict] = []
    p99_ms = shared.get("hedgedP99Ms")
    if isinstance(p99_ms, (int, float)) and p99_ms > 0:
        record = make_record(
            source=source,
            metric="fleet_hedged_p99_s",
            value=float(p99_ms) / 1000.0,
            unit="s",
            device=bench.get("device"),
            extra={
                "sharedHitRate": shared.get("sharedHitRate"),
                "healthyQPS": shared.get("healthyQPS"),
            },
        )
        record["noise_band"] = 0.5
        out.append(record)
    hit_rate = shared.get("sharedHitRate")
    if isinstance(hit_rate, (int, float)):
        out.append(
            make_record(
                source=source,
                metric="fleet_shared_hit_rate",
                value=float(hit_rate),
                unit="ratio",
                device=bench.get("device"),
                extra={"degradesRecorded": shared.get("degradesRecorded")},
            )
        )
    return out


def quant_records(bench: dict, source: str = "bench") -> List[dict]:
    """The quantized-serving numbers a bench run attached
    (``bench["quantServe"]``, from the ``BENCH_QUANT`` block —
    docs/quantization.md) as their own ledger records:

    - ``serve_table_bytes`` — resident bytes of the int8 serving table
      (codes + per-row scales), lower-better → GATED: the count is
      deterministic for a given recipe, so any growth is a real layout
      regression, not noise. The f32 twin and the compression ratio
      travel in ``extra`` so ``pio perf trend`` can show the reduction
      without a second comparable group;
    - ``quant_topk_match_rate`` — trend-only ``ratio``: the fraction of
      probe users whose int8 top-k id SET matches f32 exactly. Serving
      hard-gates this at model load (:class:`~..quant.QuantGateError`);
      the bench just measures the margin.

    A failed block (``ok`` false or an ``error`` entry) records nothing
    — its numbers measured a broken table, not the code."""
    quant = bench.get("quantServe")
    if not isinstance(quant, dict) or not quant.get("ok"):
        return []
    out: List[dict] = []
    table_bytes = quant.get("tableBytes")
    if isinstance(table_bytes, (int, float)) and table_bytes > 0:
        out.append(
            make_record(
                source=source,
                metric="serve_table_bytes",
                value=float(table_bytes),
                unit="bytes",
                device=bench.get("device"),
                extra={
                    "ratio": quant.get("ratio"),
                    "f32Bytes": quant.get("f32Bytes"),
                    "tableDtype": quant.get("tableDtype"),
                    "rank": quant.get("rank"),
                    "nItems": quant.get("nItems"),
                },
            )
        )
    match_rate = quant.get("matchRate")
    if isinstance(match_rate, (int, float)):
        out.append(
            make_record(
                source=source,
                metric="quant_topk_match_rate",
                value=float(match_rate),
                unit="ratio",
                device=bench.get("device"),
                extra={"probes": quant.get("probes"), "k": quant.get("k")},
            )
        )
    return out


def quality_records(bench: dict, source: str = "bench") -> List[dict]:
    """The model-quality numbers a bench run attached
    (``bench["quality"]``, from the in-process feedback-stream drill —
    docs/observability.md#quality) as their own trend records, so
    ``pio perf trend`` shows the quality trajectory alongside latency:

    - ``quality_score_psi`` — the live score distribution's PSI vs the
      drill's pinned baseline (unit ``psi``, trend-only: PSI is not a
      lower-is-better wall-clock, and small-sample drill PSI is too
      noisy to gate; the serving-time gate lives in the rollout plane);
    - ``quality_feedback_hitrate`` — the feedback join's hit-rate (unit
      ``ratio``, trend-only for the same reason).

    A drill that failed (``ok`` false) records nothing."""
    quality = bench.get("quality")
    if not isinstance(quality, dict) or not quality.get("ok", True):
        return []
    out: List[dict] = []
    score_psi = quality.get("scorePsi")
    if isinstance(score_psi, (int, float)):
        out.append(
            make_record(
                source=source,
                metric="quality_score_psi",
                value=float(score_psi),
                unit="psi",
                device=bench.get("device"),
            )
        )
    hit_rate = quality.get("feedbackHitRate")
    if isinstance(hit_rate, (int, float)):
        out.append(
            make_record(
                source=source,
                metric="quality_feedback_hitrate",
                value=float(hit_rate),
                unit="ratio",
                device=bench.get("device"),
                extra={
                    "samples": quality.get("feedbackSamples"),
                },
            )
        )
    return out


def alert_records(bench: dict, source: str = "bench") -> List[dict]:
    """The alert-noisiness numbers a bench run attached
    (``bench["alerts"]``, from the in-process brownout drill —
    docs/slo.md) as trend-only ledger records, so alert hygiene is
    tracked across BENCH rounds like perf and quality already are:

    - ``alert_false_positives`` — control-run fires plus flaps (unit
      ``count``, trend-only: the gate only ever compares ``unit ==
      "s"``; the drill itself is the hard gate — a noisy round fails
      tier-1, the ledger shows the trajectory).

    A drill that failed (``ok`` false) records nothing — its counts
    measured a broken drill, not the alerting plane."""
    alerts = bench.get("alerts")
    if not isinstance(alerts, dict) or not alerts.get("ok"):
        return []
    false_positives = alerts.get("falsePositives")
    if not isinstance(false_positives, (int, float)):
        return []
    return [
        make_record(
            source=source,
            metric="alert_false_positives",
            value=float(false_positives),
            unit="count",
            device=bench.get("device"),
            extra={
                "fired": alerts.get("fired"),
                "cleared": alerts.get("cleared"),
            },
        )
    ]


def ingest_records(bench: dict, source: str = "bench") -> List[dict]:
    """The ingest-scaling numbers a bench run attached
    (``bench["ingestScaling"]``, from ``loadgen --ingest-scaling`` —
    docs/storage.md#partitioning) as their own trend records:

    - ``ingest_acked_qps`` — acked event writes per second through the
      partitioned write path (unit ``qps``, higher-better → trend-only:
      the gate only ever compares ``unit == "s"``).

    The partition count travels as ``scale``, exactly like the fleet
    records carry their replica count: ``comparable_key`` groups by
    scale, so ``pio perf diff`` never gates a 4-partition run against a
    1-partition run — each N has its own trajectory. A failed drive
    (``ok`` false) records nothing."""
    scaling = bench.get("ingestScaling")
    if not isinstance(scaling, dict) or not scaling.get("ok"):
        return []
    out: List[dict] = []
    counts = scaling.get("counts") or {}
    for key in sorted(counts, key=lambda k: int(k)):
        row = counts[key] or {}
        qps = row.get("ackedQPS")
        if isinstance(qps, (int, float)) and qps > 0:
            out.append(
                make_record(
                    source=source,
                    metric="ingest_acked_qps",
                    value=float(qps),
                    unit="qps",
                    device=bench.get("device"),
                    scale=int(key),
                    extra={
                        "writers": scaling.get("writers"),
                        "acked": row.get("acked"),
                        "inProcess": scaling.get("inProcess"),
                    },
                )
            )
    return out


def migration_records(bench: dict, source: str = "bench") -> List[dict]:
    """The live-migration drill numbers a bench run attached
    (``bench["migrationDrill"]``, from ``loadgen --migrate-drill`` —
    docs/storage.md#live-migration) as trend-only ledger records:

    - ``migration_drill_wall_s`` — full drill wall clock (unit
      ``wall_s``, NOT the gated ``s``: the drill is chaos choreography
      on a possibly-contended box, a trajectory not a gate);
    - ``migration_dualwrite_overhead`` — dual-write wave wall over the
      plain-write baseline wave (unit ``ratio``) — the ingest tax of
      mirroring, the number an operator sizes the migration window by.

    The layout move travels as ``scale`` verbatim (``"2->3"``):
    ``comparable_key`` groups by scale, so a 2→3 expansion and a 3→2
    merge never share a trajectory. A failed drill (``ok`` false)
    records nothing — its timings measured a broken run."""
    block = bench.get("migrationDrill")
    if not isinstance(block, dict) or not block.get("ok"):
        return []
    out: List[dict] = []
    scale = f"{block.get('oldPartitions')}->{block.get('newPartitions')}"
    extra = {
        k: block[k]
        for k in ("opsPerPhase", "lostAckedWrites", "duplicateFolds")
        if k in block
    }
    wall = block.get("wallS")
    if isinstance(wall, (int, float)) and wall > 0:
        out.append(
            make_record(
                source=source,
                metric="migration_drill_wall_s",
                value=float(wall),
                unit="wall_s",
                device=bench.get("device"),
                scale=scale,
                extra=extra,
            )
        )
    overhead = block.get("dualWriteOverhead")
    if isinstance(overhead, (int, float)) and overhead > 0:
        out.append(
            make_record(
                source=source,
                metric="migration_dualwrite_overhead",
                value=float(overhead),
                unit="ratio",
                device=bench.get("device"),
                scale=scale,
                extra=extra,
            )
        )
    return out


def sharded_records(bench: dict, source: str = "bench") -> List[dict]:
    """The sharded-train numbers a bench run attached
    (``bench["shardedTrain"]``, from the forced-virtual-device subprocess
    drive — docs/distributed_training.md) as their own ledger records:

    - ``train_sharded_s`` — wall-clock of the small sharded recipe (unit
      ``s``, lower-better → gated), with the SHARD COUNT as ``scale``
      exactly like ``ingest_acked_qps`` carries its partition count:
      ``comparable_key`` groups by scale, so ``pio perf diff`` never
      gates a 4-shard run against a 1-shard run — each N has its own
      trajectory. Records declare a wide ``noise_band`` (0.5): the drive
      is a subprocess on a possibly-contended CI box, so only a collapse
      should fire the gate, not scheduler weather.

    A failed drive (``ok`` false) records nothing — its wall-clock
    measured a broken run, not the code."""
    block = bench.get("shardedTrain")
    if not isinstance(block, dict) or not block.get("ok"):
        return []
    out: List[dict] = []
    counts = block.get("counts") or {}
    for key in sorted(counts, key=lambda k: int(k)):
        row = counts[key] or {}
        train_s = row.get("trainS")
        if isinstance(train_s, (int, float)) and train_s > 0:
            record = make_record(
                source=source,
                metric="train_sharded_s",
                value=float(train_s),
                unit="s",
                device=row.get("device"),
                scale=int(key),
                levers={
                    "solve_mode": row.get("solve_mode", "chunked"),
                    "gather_dtype": row.get("gather_dtype", "f32"),
                    "sort_gather": bool(row.get("sort_gather", True)),
                    "fused_gather": bool(row.get("fused_gather", False)),
                    "fallback": "",
                },
                rmse=row.get("rmse"),
                extra={
                    k: row[k]
                    for k in ("nnz", "iterations", "flopImbalance")
                    if k in row
                },
            )
            record["noise_band"] = 0.5
            out.append(record)
    return out


def ckpt_records(bench: dict, source: str = "bench") -> List[dict]:
    """The preemption-drill numbers a bench run attached
    (``bench["ckptResume"]``, from the SIGKILL + cross-shard-resume
    subprocess drive — docs/checkpoint.md#preemption-drill) as
    trend-only ledger records:

    - ``train_ckpt_overhead_ratio`` — checkpointed wall / plain wall of
      the same recipe at the same shard count (unit ``ratio``,
      deliberately NOT ``s``: the gate only compares lower-is-better
      ``s``/``bytes`` units, and the cost of never losing a run must
      never fail a perf gate on a contended CI box — the trajectory is
      the product). The resume wall, snapshot seconds, writer counters
      and the factor-equivalence evidence ride in ``extra`` so a
      creeping overhead or a tolerance near-miss is visible in history.

    The metric name is this family's namespace: ``comparable_key``
    groups by metric first, so these records can never gate — or be
    gated by — the ``train_sharded_s``/``quant``/``fleet`` families.
    A failed drill (``ok`` false) records nothing — its ratio measured
    a broken resume, not the writer."""
    block = bench.get("ckptResume")
    if not isinstance(block, dict) or not block.get("ok"):
        return []
    ratio = block.get("overheadRatio")
    if not isinstance(ratio, (int, float)) or ratio <= 0:
        return []
    return [
        make_record(
            source=source,
            metric="train_ckpt_overhead_ratio",
            value=float(ratio),
            unit="ratio",
            device=block.get("device"),
            scale=block.get("resumeShards"),
            extra={
                k: block[k]
                for k in (
                    "trainShards", "killStep", "resumedFrom", "resumeS",
                    "plainS", "ckptS", "snapshotS", "written", "dropped",
                    "errors", "maxAbsDiff",
                )
                if k in block
            },
        )
    ]


def lint_records(bench: dict, source: str = "bench") -> List[dict]:
    """The lint-sweep timings a bench run attached (``bench["lintSweep"]``,
    from the in-process cold-vs-warm engine drive — docs/lint.md#cache)
    as trend-only ledger records:

    - ``lint_wall_s`` — cold full-package sweep wall-clock (unit
      ``wall_s``, deliberately NOT ``s``: the gate only ever compares
      ``unit == "s"``, and a lint sweep on a contended CI box must
      never fail a perf gate — the trajectory is the product). The warm
      wall-clock, file count, and the byte-identity verdict ride along
      in ``extra`` so a cache regression (warm ≈ cold, or
      ``identical: false``) is visible in the ledger history.

    A failed sweep (``ok`` false) records nothing — its wall-clock
    measured a broken engine run, not the linter."""
    block = bench.get("lintSweep")
    if not isinstance(block, dict) or not block.get("ok"):
        return []
    cold_s = block.get("coldS")
    if not isinstance(cold_s, (int, float)) or cold_s <= 0:
        return []
    return [
        make_record(
            source=source,
            metric="lint_wall_s",
            value=float(cold_s),
            unit="wall_s",
            device=bench.get("device"),
            extra={
                "warmS": block.get("warmS"),
                "files": block.get("files"),
                "identical": block.get("identical"),
            },
        )
    ]


def append_record(path: str, record: dict) -> None:
    """Append one record as a JSON line, fsynced — the ledger is the
    durable evidence trail, a torn tail must cost at most one line."""
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    line = json.dumps(record, sort_keys=True) + "\n"
    with open(path, "a", encoding="utf-8") as fh:
        fh.write(line)
        fh.flush()
        os.fsync(fh.fileno())


def load_ledger(path: str) -> List[dict]:
    """Every parseable record in file order; unparseable lines (a torn
    append, hand-editing damage) are skipped, never fatal."""
    records: List[dict] = []
    try:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    parsed = json.loads(line)
                except ValueError:
                    continue
                if isinstance(parsed, dict) and "value" in parsed:
                    records.append(parsed)
    except OSError:
        return []
    return records


def load_bench_history(history_dir: str) -> List[dict]:
    """The checked-in ``BENCH_r0*.json`` driver records, normalized and
    ordered by round. A round whose bench failed outright (``parsed``
    null — the r01 bring-up failure) contributes nothing."""
    records: List[dict] = []
    for path in sorted(glob.glob(os.path.join(history_dir, "BENCH_r*.json"))):
        match = _BENCH_ROUND_RE.search(os.path.basename(path))
        if not match:
            continue
        try:
            with open(path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            continue
        parsed = doc.get("parsed") if isinstance(doc, dict) else None
        if not isinstance(parsed, dict):
            continue
        records.append(
            bench_to_record(parsed, source=f"bench_r{int(match.group(1)):02d}")
        )
    return records


def _device_class(device: Optional[str]) -> str:
    text = (device or "").lower()
    if "tpu" in text:
        return "tpu"
    if "cpu" in text:
        return "cpu"
    if "gpu" in text or "cuda" in text:
        return "gpu"
    return text or "unknown"


def comparable_key(record: dict) -> Tuple:
    """Records sharing this key measure the same thing and may gate each
    other: metric, device *class* (chip generations differ less than a
    CPU fallback differs from any chip), scale, and every lever flag."""
    levers = record.get("levers") or {}
    return (
        record.get("metric"),
        _device_class(record.get("device")),
        record.get("scale"),
        levers.get("solve_mode", "auto"),
        levers.get("gather_dtype", "f32"),
        bool(levers.get("sort_gather", False)),
        bool(levers.get("fused_gather", False)),
    )


def _median(values: List[float]) -> float:
    ordered = sorted(values)
    n = len(ordered)
    mid = n // 2
    return (
        ordered[mid]
        if n % 2
        else (ordered[mid - 1] + ordered[mid]) / 2.0
    )


def _key_dict(key: Tuple) -> dict:
    """A comparable key rendered as the verdict dict both gates share."""
    return {
        "metric": key[0],
        "device_class": key[1],
        "scale": key[2],
        "solve_mode": key[3],
        "gather_dtype": key[4],
        "sort_gather": key[5],
        "fused_gather": key[6],
    }


def _gateable_groups(records: List[dict]) -> Dict[Tuple, List[dict]]:
    """Records eligible for the regression gate, grouped by comparable
    key in given (= chronological) order: lower-is-better units only
    (seconds, plus deterministic byte counts like ``serve_table_bytes``),
    failed runs (value -1) and error-carrying runs excluded — a
    quality-gate failure carries a real (positive) wall time but
    measured an invalid run, so it must neither be gated nor pollute a
    baseline median."""
    groups: Dict[Tuple, List[dict]] = {}
    for record in records:
        if record.get("unit", "s") not in ("s", "bytes"):
            continue
        value = record.get("value")
        if not isinstance(value, (int, float)) or value <= 0:
            continue
        if record.get("error") or (record.get("extra") or {}).get("error"):
            continue
        groups.setdefault(comparable_key(record), []).append(record)
    return groups


#: ``find_no_prior`` only reports groups whose latest record sits
#: within this many trailing records — an abandoned one-off lever
#: experiment ages out of the diff output once enough newer evidence
#: lands, instead of printing a stale "no comparable prior" forever.
NO_PRIOR_RECENT_WINDOW = 12


def find_no_prior(
    records: List[dict],
    min_history: int = MIN_HISTORY,
    recent_window: int = NO_PRIOR_RECENT_WINDOW,
) -> List[dict]:
    """Gate-able groups whose latest record has FEWER than
    ``min_history`` predecessors — measured, but with nothing honest to
    compare against. Distinct from "stable": lever flags are part of
    the comparable key, so flipping a default starts a fresh group and
    a silent exit-0 would read as "no regression" when the truth is
    "no baseline yet" (``pio perf diff`` prints these explicitly —
    docs/performance.md#perf-ledger). One verdict dict per group, with
    the history count the group still needs. Only groups still ACTIVE
    — latest record within the trailing ``recent_window`` gate-able
    records — are reported, so a forgotten one-off experiment stops
    cluttering the diff once newer evidence buries it."""
    groups = _gateable_groups(records)
    # recency = position in the gate-able stream (same record objects
    # the groups hold, so id() is a stable key even for duplicates)
    gateable_ids = {id(r) for g in groups.values() for r in g}
    positions: Dict[int, int] = {}
    for record in records:
        if id(record) in gateable_ids and id(record) not in positions:
            positions[id(record)] = len(positions)
    total = len(positions)
    out: List[dict] = []
    for key, group in groups.items():
        if len(group) >= min_history + 1:
            continue
        latest = group[-1]
        if total > recent_window and (
            positions.get(id(latest), total) < total - recent_window
        ):
            continue  # stale experiment: aged out of the report
        out.append(
            {
                "key": _key_dict(key),
                "latest": float(latest["value"]),
                "latest_source": latest.get("source"),
                "history": len(group) - 1,
                "needed": min_history,
            }
        )
    return out


def detect_regressions(
    records: List[dict],
    noise_band: float = DEFAULT_NOISE_BAND,
    min_history: int = MIN_HISTORY,
) -> List[dict]:
    """Per comparable group (records in given = chronological order):
    compare the latest value against the median of its predecessors.
    Lower-is-better (``unit in ("s", "bytes")``; other units are
    trend-only).
    A record may carry its own ``noise_band`` (a noisier measurement —
    the fleet drive's small-sample p99); the group's effective band is
    the WIDER of it and the caller's, so a noisy metric can never be
    held to a tighter bar than its producer declared. Returns one
    verdict dict per flagged group — empty means clean (groups without
    enough history are NOT clean, they are unestablished — see
    :func:`find_no_prior`)."""
    groups = _gateable_groups(records)
    flagged: List[dict] = []
    for key, group in groups.items():
        if len(group) < min_history + 1:
            continue
        latest = group[-1]
        prior = [float(r["value"]) for r in group[:-1]]
        baseline = _median(prior)
        if baseline <= 0:
            continue
        try:
            declared = max(
                float(r.get("noise_band", 0.0) or 0.0) for r in group
            )
        except (TypeError, ValueError):
            declared = 0.0  # a hand-edited band never breaks the gate
        band = max(noise_band, declared)
        ratio = float(latest["value"]) / baseline
        if ratio > 1.0 + band:
            flagged.append(
                {
                    "key": _key_dict(key),
                    "latest": float(latest["value"]),
                    "latest_source": latest.get("source"),
                    "baseline_median": round(baseline, 4),
                    "ratio": round(ratio, 4),
                    "noise_band": band,
                    "history": len(prior),
                }
            )
    return flagged


def render_trend(records: List[dict]) -> str:
    """The full trajectory, grouped by comparable key, chronological
    within each group — the ``pio perf trend`` table."""
    if not records:
        return "(no performance records)"
    groups: Dict[Tuple, List[dict]] = {}
    for record in records:
        groups.setdefault(comparable_key(record), []).append(record)
    lines: List[str] = []
    for key in sorted(groups, key=str):
        metric, device_class, scale = key[0], key[1], key[2]
        levers = (
            f"solve={key[3]} gather={key[4]}"
            + (" sort" if key[5] else "")
            + (" fused" if key[6] else "")
        )
        lines.append(
            f"{metric} [{device_class} scale={scale} {levers}]"
        )
        for record in groups[key]:
            # a foreign/hand-edited line may carry non-numeric fields;
            # the trend must render around it, never traceback
            value = record.get("value", 0.0)
            if not isinstance(value, (int, float)):
                continue
            rmse = record.get("rmse")
            vs = record.get("vs_baseline")
            lines.append(
                f"  {record.get('source', '?'):<14}"
                f"{value:>10.3f} {record.get('unit', 's')}"
                + (
                    f"  vs_baseline={vs:g}"
                    if isinstance(vs, (int, float))
                    else ""
                )
                + (
                    f"  rmse={rmse:g}"
                    if isinstance(rmse, (int, float))
                    else ""
                )
            )
    return "\n".join(lines)
