"""``pio top`` / ``pio trace``: fleet-wide scrape-and-render CLIs.

``pio top`` pulls ``GET /metrics`` from a node list and renders one
screenful of fleet state — the operator's first question ("is anything
shedding / lagging / degraded?") answered without opening a dashboard.
``pio trace <id>`` pulls ``GET /traces.json`` from the same node list
and stitches every process's spans for one ``X-PIO-Trace`` id into a
single start-time-ordered timeline.

Both are read-only scrapers over the observability plane's two wire
surfaces (``docs/observability.md``) — they need no storage conf, no
jax, and work against any mix of query / event / storage / dashboard
nodes (a node that lacks a given metric just shows ``-``).
"""

from __future__ import annotations

import http.client
import json
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from .expo import parse_text
from .metrics import percentile_from_buckets

#: default node list: one of each server on localhost (query, event,
#: storage) — the quickstart topology
DEFAULT_NODES = "localhost:8000,localhost:7070,localhost:7079"


def _split_nodes(spec: str) -> List[str]:
    return [n.strip() for n in spec.split(",") if n.strip()]


def _fetch(node: str, path: str, timeout: float = 5.0) -> Optional[str]:
    """One GET against ``host:port`` → body, or None for anything short
    of a 200 — a dead node, a garbled node spec, a non-HTTP peer. One
    bad fleet member must render as DOWN, never crash the whole table."""
    host, _, port = node.partition(":")
    try:
        conn = http.client.HTTPConnection(
            host, int(port or 80), timeout=timeout
        )
    except (ValueError, OSError):  # 'host:abc', empty host, ...
        return None
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        body = resp.read().decode("utf-8", "replace")
        return body if resp.status == 200 else None
    except (OSError, http.client.HTTPException, ValueError):
        return None
    finally:
        conn.close()


def fetch_metrics(node: str, timeout: float = 5.0) -> Optional[Dict]:
    """``GET /metrics`` on ``host:port`` → parsed samples (None when the
    node is down). Shared by ``pio top`` and ``loadgen``."""
    body = _fetch(node, "/metrics", timeout=timeout)
    return None if body is None else parse_text(body)


def merge_histogram_buckets(
    samples: Optional[Sequence[Tuple[Dict[str, str], float]]],
) -> Optional[Tuple[List[float], List[int]]]:
    """Scraped ``<name>_bucket`` samples (all label sets summed) →
    ``(bounds, cumulative)`` ready for :func:`percentile_from_buckets`;
    None without usable buckets."""
    if not samples:
        return None
    merged: Dict[float, float] = {}
    for labels, value in samples:
        le = labels.get("le")
        if le is None:
            continue
        try:
            bound = float("inf") if le == "+Inf" else float(le)
        except ValueError:
            continue
        merged[bound] = merged.get(bound, 0.0) + value
    bounds = sorted(merged)
    if not bounds:
        return None
    return bounds, [int(merged[b]) for b in bounds]


# -- pio top ----------------------------------------------------------------


def _series_sum(
    metrics: Dict[str, List[Tuple[Dict[str, str], float]]],
    name: str,
    **match: str,
) -> Optional[float]:
    samples = metrics.get(name)
    if samples is None:
        return None
    total, found = 0.0, False
    for labels, value in samples:
        if all(labels.get(k) == v for k, v in match.items()):
            total += value
            found = True
    return total if found else None


def _hist_percentile(
    metrics: Dict[str, List[Tuple[Dict[str, str], float]]],
    name: str,
    q: float,
) -> Optional[float]:
    """Percentile estimate from a scraped histogram's ``_bucket`` series
    (all label sets merged — fleet-table altitude)."""
    hist = merge_histogram_buckets(metrics.get(f"{name}_bucket"))
    if hist is None:
        return None
    bounds, cums = hist
    return percentile_from_buckets(bounds, cums, q)


def node_row(node: str, timeout: float = 5.0) -> Dict[str, object]:
    """One fleet-table row: scrape + digest a node's exposition."""
    m = fetch_metrics(node, timeout=timeout)
    if m is None:
        return {"node": node, "up": False}
    row: Dict[str, object] = {"node": node, "up": True}
    row["requests"] = _series_sum(m, "pio_serving_request_seconds_count")
    if row["requests"] is None:  # router nodes: end-to-end routed reqs
        row["requests"] = _series_sum(m, "pio_router_request_seconds_count")
    if row["requests"] is None:  # non-serving nodes: total HTTP responses
        row["requests"] = _series_sum(m, "pio_http_responses_total")
    for q, key in ((0.5, "p50_ms"), (0.99, "p99_ms")):
        p = _hist_percentile(m, "pio_serving_request_seconds", q)
        if p is None:
            p = _hist_percentile(m, "pio_router_request_seconds", q)
        if p is None:
            p = _hist_percentile(m, "pio_storage_op_seconds", q)
        if p is None:
            p = _hist_percentile(m, "pio_http_request_seconds", q)
        row[key] = None if p is None else p * 1000.0
    row["shed"] = _series_sum(m, "pio_serving_events_total", kind="shed")
    if row["shed"] is None:  # router nodes shed at their per-app quotas
        row["shed"] = _series_sum(m, "pio_router_shed_total")
    breakers = m.get("pio_breaker_state")
    row["breakers_open"] = (
        None
        if breakers is None
        else sum(1 for _labels, v in breakers if v > 0)
    )
    row["batch_avg"] = None
    submitted = _series_sum(m, "pio_batch_items_total")
    batches = _series_sum(m, "pio_batch_flush_total")
    if submitted is not None and batches:
        row["batch_avg"] = submitted / batches
    row["lag"] = _series_sum(m, "pio_replication_lag_ops")
    row["seq"] = _series_sum(m, "pio_changefeed_seq")
    # partitioned write path (docs/storage.md#partitioning): one PARTS
    # cell per node — an ingest node shows how many event-store
    # partitions its client view can reach ("2/3"), a storage node its
    # own keyspace slot ("p1/3"); nodes without the route show '-'
    row["parts"] = _partition_cell(node, timeout=timeout)
    row["train_s"] = _series_sum(m, "pio_train_phase_seconds")
    # continuous-learning freshness (docs/continuous.md): how far the
    # model lags the feedback stream, fleet-wide at a glance
    row["feed_lag"] = _series_sum(m, "pio_continuous_feed_lag_ops")
    row["cand_age"] = _series_sum(
        m, "pio_continuous_candidate_age_seconds"
    )
    # jit telemetry (docs/observability.md#profiling): compiles are
    # expected at warmup; a non-zero RETRACE column on a steady-state
    # server is the shape-bucketing regression alarm
    row["jit_compiles"] = _series_sum(m, "pio_jit_compiles_total")
    row["jit_retraces"] = _series_sum(m, "pio_jit_retraces_total")
    # router tier (docs/fleet.md): healthy-backend count, plus reads the
    # router had to retry on another replica — the fleet-failover pulse
    row["backends_up"] = _series_sum(m, "pio_router_backends_up")
    row["router_retries"] = _series_sum(m, "pio_router_retries_total")
    # router response cache (docs/fleet.md#cache): hit rate over actual
    # lookups — a router that has seen none (cache off, or no traffic)
    # shows '-', never a measured 0.00
    cache_hits = _series_sum(m, "pio_router_cache_hits_total")
    cache_misses = _series_sum(m, "pio_router_cache_misses_total")
    row["cache_hit_rate"] = None
    if cache_hits is not None and cache_misses is not None:
        lookups = cache_hits + cache_misses
        if lookups > 0:
            row["cache_hit_rate"] = cache_hits / lookups
    # quality plane (docs/observability.md#quality): the live model's
    # served-score drift vs its pinned baseline, and the feedback join's
    # hit-rate; event-server nodes show their worst per-app mix PSI in
    # the same DRIFT column (one drift number per node, whatever the
    # node's plane)
    row["score_psi"] = _series_sum(
        m, "pio_quality_score_psi", variant="baseline"
    )
    if row["score_psi"] is not None and row["score_psi"] < 0:
        row["score_psi"] = None  # -1 sentinel: the monitor is abstaining
    if row["score_psi"] is None:
        mix = [
            value
            for _labels, value in m.get("pio_quality_event_mix_psi") or []
            if value >= 0  # -1 sentinel: that app's mix is abstaining
        ]
        if mix:
            row["score_psi"] = max(mix)
    # health plane (docs/slo.md): one HEALTH cell per node — FIRING
    # objective count beats a stall beats ok; a node with no SLO engine
    # (pre-health build) shows '-' like every other absent column
    alert_states = m.get("pio_slo_alert_state")
    stalls = _series_sum(m, "pio_stall_detected_total")
    if alert_states is None:
        row["health"] = None
    else:
        firing = sum(1 for _labels, v in alert_states if v == 1)
        if firing:
            row["health"] = f"ALERT:{firing}"
        elif stalls:
            row["health"] = f"STALL:{int(stalls)}"
        else:
            row["health"] = "ok"
    row["hit_rate"] = _series_sum(m, "pio_quality_feedback_hit_rate")
    joined = (
        _series_sum(
            m, "pio_quality_feedback_events_total", outcome="hit"
        )
        or 0
    ) + (
        _series_sum(
            m, "pio_quality_feedback_events_total", outcome="miss"
        )
        or 0
    )
    if not joined:
        # the rate is over JOINED events only — a backlog of unjoined
        # feedback must not read as a measured 0.00 hit-rate
        row["hit_rate"] = None
    return row


def _partition_cell(node: str, timeout: float = 5.0) -> Optional[str]:
    """``GET /replication.json`` → the PARTS cell, or None when the
    node lacks the route / reports no partition rows."""
    body = _fetch(node, "/replication.json", timeout=timeout)
    if body is None:
        return None
    try:
        doc = json.loads(body)
    except ValueError:
        return None
    rows = (doc or {}).get("partitions") or []
    if not rows:
        return None
    first = rows[0]
    if "role" in first:
        # a storage node reporting its own slot
        return f"p{first.get('partition', 0)}/{first.get('of', 1)}"
    total = max(int(r.get("of", len(rows))) for r in rows)
    up = sum(1 for r in rows if r.get("up"))
    return f"{up}/{total}"


_COLUMNS = (
    ("NODE", "node", "{}"),
    ("UP", "up", "{}"),
    ("REQS", "requests", "{:.0f}"),
    ("P50MS", "p50_ms", "{:.2f}"),
    ("P99MS", "p99_ms", "{:.2f}"),
    ("SHED", "shed", "{:.0f}"),
    ("BRKOPEN", "breakers_open", "{}"),
    ("BATCH", "batch_avg", "{:.1f}"),
    ("LAG", "lag", "{:.0f}"),
    ("SEQ", "seq", "{:.0f}"),
    ("PARTS", "parts", "{}"),
    ("TRAIN_S", "train_s", "{:.2f}"),
    ("FEEDLAG", "feed_lag", "{:.0f}"),
    ("CANDAGE", "cand_age", "{:.0f}"),
    ("JITC", "jit_compiles", "{:.0f}"),
    ("RETRACE", "jit_retraces", "{:.0f}"),
    ("BACKENDS", "backends_up", "{:.0f}"),
    ("RTRETRY", "router_retries", "{:.0f}"),
    ("CACHE", "cache_hit_rate", "{:.2f}"),
    ("DRIFT", "score_psi", "{:.3f}"),
    ("HITRATE", "hit_rate", "{:.2f}"),
    ("HEALTH", "health", "{}"),
)

#: public alias for other fleet renderers (the dashboard's /fleet panel)
FLEET_COLUMNS = _COLUMNS


def format_cell(value: object, fmt: str) -> str:
    """One fleet-table cell, shared by every renderer of
    :data:`FLEET_COLUMNS` (``pio top`` and the dashboard's ``/fleet``
    panel must show the same row the same way)."""
    if value is None:
        return "-"
    if isinstance(value, bool):
        return "up" if value else "DOWN"
    return fmt.format(value)


def format_row(row: Dict[str, object]) -> List[str]:
    """A scraped node row → one cell per :data:`FLEET_COLUMNS` entry."""
    return [
        format_cell(row.get(key), fmt) for _title, key, fmt in _COLUMNS
    ]


def render_table(rows: Sequence[Dict[str, object]]) -> str:
    table: List[List[str]] = [[title for title, _, _ in _COLUMNS]]
    for row in rows:
        table.append(format_row(row))
    widths = [max(len(r[i]) for r in table) for i in range(len(_COLUMNS))]
    return "\n".join(
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in table
    )


def run_top(
    nodes: str = DEFAULT_NODES, timeout: float = 5.0, as_json: bool = False
) -> int:
    rows = [node_row(n, timeout=timeout) for n in _split_nodes(nodes)]
    if as_json:
        print(json.dumps(rows, default=str))
    else:
        print(render_table(rows))
    return 0 if any(r.get("up") for r in rows) else 1


# -- pio trace --------------------------------------------------------------


def collect_trace(
    trace_id: str, nodes: str = DEFAULT_NODES, timeout: float = 5.0
) -> List[dict]:
    """All spans for ``trace_id`` across the node list, start-ordered."""
    spans: List[dict] = []
    for node in _split_nodes(nodes):
        body = _fetch(node, "/traces.json", timeout=timeout)
        if body is None:
            continue
        try:
            doc = json.loads(body)
        except ValueError:
            continue
        for span in doc.get("spans", []):
            if span.get("traceId") == trace_id:
                span = dict(span)
                span.setdefault("node", node)
                spans.append(span)
    spans.sort(key=lambda s: (s.get("startMs", 0), s.get("spanId", "")))
    return spans


def render_trace(trace_id: str, spans: Sequence[dict]) -> str:
    if not spans:
        return f"trace {trace_id}: no spans found"
    t0 = min(s.get("startMs", 0) for s in spans)
    lines = [f"trace {trace_id}: {len(spans)} spans"]
    for s in spans:
        offset = s.get("startMs", 0) - t0
        err = f"  ERROR={s['error']}" if s.get("error") else ""
        tags = s.get("tags")
        tag_str = (
            "  " + " ".join(f"{k}={v}" for k, v in sorted(tags.items()))
            if tags
            else ""
        )
        lines.append(
            f"  +{offset:9.3f}ms  {s.get('durationMs', 0):9.3f}ms  "
            f"{s.get('service', '?'):<14} {s.get('name', '?')}"
            f"{tag_str}{err}"
        )
    return "\n".join(lines)


def run_trace(
    trace_id: str,
    nodes: str = DEFAULT_NODES,
    timeout: float = 5.0,
    as_json: bool = False,
) -> int:
    spans = collect_trace(trace_id, nodes, timeout=timeout)
    if as_json:
        print(json.dumps(spans))
    else:
        print(render_trace(trace_id, spans))
    return 0 if spans else 1


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="pio top")
    p.add_argument("--nodes", default=DEFAULT_NODES)
    p.add_argument("--json", action="store_true")
    p.add_argument("--timeout", type=float, default=5.0)
    args = p.parse_args(argv)
    return run_top(args.nodes, timeout=args.timeout, as_json=args.json)


if __name__ == "__main__":
    sys.exit(main())
