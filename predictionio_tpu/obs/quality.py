"""Model & data quality monitors: score drift, feedback quality, ingest mix.

The decision half of the quality-observability plane
(``docs/observability.md#quality``), built on the pure sketches in
:mod:`predictionio_tpu.obs.sketch`. Three signal families:

1. **Served-score distribution drift** — :class:`QualityMonitor` keeps a
   rolling per-variant sketch of the top-k scores the serving path
   produced, pins a *baseline snapshot* of the live distribution once it
   has ``pin_min_samples`` (and re-pins after every model go-LIVE), and
   scores each variant's current window against the pin via PSI:
   ``pio_quality_score_psi{variant}`` plus quantile gauges. The rollout
   plane reads the candidate's PSI as an optional gate
   (``GateConfig.max_score_psi``, docs/rollouts.md).
2. **Feedback-derived online quality** — the serving path records what
   was served per user (a bounded LRU); ``pio_pr``-adjacent feedback
   events (rate/buy, joined by the continuous plane's feed watcher) look
   the user up and record whether the item they acted on was in their
   served list and at which rank: hit-rate + served-rank sketch — a real
   online-quality number next to the offline divergence gate
   (docs/continuous.md).
3. **Ingest data quality** — :class:`IngestQualityMonitor` rides the
   Event Server: per-app schema-violation / out-of-range / poison-event
   counters and an event-type *mix* sketch compared against a durable
   per-app baseline via categorical PSI
   (``pio_quality_event_mix_psi{app}``).

Everything here runs on injected clocks, takes one lock per monitor
(gauge callbacks lock like every other cross-thread reader), and never
blocks under that lock — snapshot/baseline file writes happen outside
it. Snapshots are schema-versioned JSONL lines (fsynced, torn lines
skipped on load), appended next to the perf ledger so the quality
trajectory is durable evidence the same way the perf trajectory is.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .sketch import QuantileSketch, categorical_psi, psi

__all__ = [
    "QualityConfig",
    "QualityMonitor",
    "IngestQualityMonitor",
    "SNAPSHOT_SCHEMA",
    "SNAPSHOTS_ENV",
    "append_snapshot",
    "USER_KEY_FIELDS",
    "feedback_key",
    "load_snapshots",
    "scores_from_result",
    "snapshot_psi",
]

SNAPSHOT_SCHEMA = 1

#: env naming the JSONL file quality snapshots append to (the quality
#: twin of ``PIO_PERF_LEDGER`` — both live next to the perf ledger)
SNAPSHOTS_ENV = "PIO_QUALITY_SNAPSHOTS"

#: variant vocabulary mirrored from rollout/plan.py WITHOUT importing it
#: (obs must stay importable with zero package dependencies beyond obs)
_BASELINE = "baseline"
_CANDIDATE = "candidate"


@dataclasses.dataclass(frozen=True)
class QualityConfig:
    """Policy knobs of one process's quality monitors."""

    #: rolling-window length for the score / mix distributions (two
    #: epochs are kept, so signals cover 1–2 windows of history)
    window_s: float = 600.0
    #: live-traffic samples before the baseline snapshot auto-pins
    pin_min_samples: int = 200
    #: samples BOTH sides of a PSI comparison need before it reports —
    #: a 5-sample "distribution" would make the gate a coin flip
    min_psi_samples: int = 50
    #: sketch relative accuracy (docs/observability.md#quality)
    rel_err: float = 0.02
    #: served-list LRU capacity for the feedback join (per process;
    #: bounded — the join is sampling, not an index)
    served_capacity: int = 1024
    #: quantiles exported as ``pio_quality_score_quantile{variant,q}``
    quantiles: Tuple[float, ...] = (0.5, 0.9, 0.99)
    #: JSONL path quality snapshots append to; None reads SNAPSHOTS_ENV
    #: at write time (unset = no snapshot persistence)
    snapshot_path: Optional[str] = None
    #: accepted rating interval at ingest; outside counts as a "range"
    #: violation (the event is still stored — observability, not veto)
    rating_range: Tuple[float, float] = (0.0, 10.0)
    #: ingest events per app before the mix baseline auto-pins
    baseline_min_events: int = 200


#: conventional user-identity payload fields, most specific first — the
#: ONE home for this order (the VARIANT_HEADER lesson: a second copy
#: silently diverges and every feedback event goes "unjoined");
#: ``rollout.plan._ENTITY_KEY_FIELDS`` extends it with item/id
#: fallbacks for sticky assignment of non-user-keyed payloads
USER_KEY_FIELDS: Tuple[str, ...] = (
    "user", "userId", "user_id", "uid", "entityId", "entity_id",
)


def feedback_key(payload) -> str:
    """The identity feedback joins on: the conventional user field of a
    query payload (the same field order ``rollout.plan.sticky_key``
    prefers), or the stringified value itself — the continuous plane
    passes the feedback event's raw user id through here so both sides
    derive the same key."""
    if isinstance(payload, dict):
        for field in USER_KEY_FIELDS:
            value = payload.get(field)
            if isinstance(value, (str, int, float, bool)):
                return str(value)
        try:
            return json.dumps(payload, sort_keys=True, default=str)
        except (TypeError, ValueError):
            return str(payload)
    return str(payload)


def scores_from_result(result) -> Tuple[List, List[float]]:
    """Extract ``(items, scores)`` from one *encoded* prediction. The
    recommender templates' ``{"itemScores": [{"item", "score"}, ...]}``
    shape first; a bare ``{"score": x}`` scalar second; anything else
    contributes nothing (a classification label has no score
    distribution to drift)."""
    if not isinstance(result, dict):
        return [], []
    item_scores = result.get("itemScores")
    if isinstance(item_scores, list):
        items: List = []
        scores: List[float] = []
        for entry in item_scores:
            if not isinstance(entry, dict):
                continue
            score = entry.get("score")
            if isinstance(score, (int, float)) and not isinstance(
                score, bool
            ):
                items.append(entry.get("item"))
                scores.append(float(score))
        return items, scores
    score = result.get("score")
    if isinstance(score, (int, float)) and not isinstance(score, bool):
        return [result.get("item")], [float(score)]
    return [], []


class _RollingPair:
    """Two-epoch rotation of any mergeable container: ``current`` takes
    new observations, ``previous`` ages out after ``window_s`` — so a
    combined read always covers between one and two windows of history
    at bounded memory, with no per-sample timestamps. NOT thread-safe:
    the owning monitor's lock guards every call."""

    def __init__(self, clock: Callable[[], float], window_s: float, make):
        self._clock = clock
        self._window_s = window_s
        self._make = make
        self.current = make()
        self.previous = make()
        self._epoch = clock()

    def rotate(self) -> None:
        now = self._clock()
        elapsed = now - self._epoch
        if elapsed < self._window_s:
            return
        if elapsed >= 2.0 * self._window_s:
            self.previous = self._make()  # idle gap: both epochs stale
        else:
            self.previous = self.current
        self.current = self._make()
        self._epoch = now


class QualityMonitor:
    """Serving-side quality monitor: score drift + feedback join.

    One per :class:`~predictionio_tpu.workflow.serving.QueryServer`;
    the serving path records every answered query, the rollout manager
    records shadow candidates' answers, and the continuous plane feeds
    user feedback events into :meth:`record_feedback`.
    """

    def __init__(
        self,
        metrics,
        clock: Callable[[], float] = time.monotonic,
        config: Optional[QualityConfig] = None,
    ):
        self.config = config or QualityConfig()
        self.clock = clock
        self._lock = threading.Lock()
        cfg = self.config
        self._windows: Dict[str, _RollingPair] = {
            _BASELINE: self._fresh_window(),
            _CANDIDATE: self._fresh_window(),
        }
        #: the distribution pinned at model LIVE (docs/observability.md)
        self._pinned: Optional[QuantileSketch] = None
        self._served: "OrderedDict[str, List]" = OrderedDict()
        self._feedback_hits = 0
        self._feedback_total = 0
        self._rank_sketch = self._make_sketch()

        self._feedback_events = metrics.counter(
            "pio_quality_feedback_events_total",
            "Feedback events joined to served lists, by outcome",
            labelnames=("outcome",),
        )
        # variant / q are closed vocabularies: safe labels
        for variant in (_BASELINE, _CANDIDATE):
            metrics.gauge_callback(
                "pio_quality_score_psi",
                (
                    lambda v=variant: (
                        p if (p := self.score_psi(v)) is not None else -1.0
                    )
                ),
                "Served-score PSI vs the pinned baseline snapshot "
                "(-1 = abstaining: no pin yet or not enough samples)",
                labels={"variant": variant},
            )
            metrics.gauge_callback(
                "pio_quality_score_samples",
                (lambda v=variant: self._window_count(v)),
                "Score samples in the rolling window",
                labels={"variant": variant},
            )
            for q in cfg.quantiles:
                metrics.gauge_callback(
                    "pio_quality_score_quantile",
                    (lambda v=variant, qq=q: self.score_quantile(v, qq)),
                    "Served-score quantiles over the rolling window",
                    # pio: lint-ok[obs-unbounded-label] q ranges over config.quantiles — a tuple fixed at construction (default 3 values), a closed vocabulary the AST cannot see through the f-string
                    labels={"variant": variant, "q": f"{q:g}"},
                )
        metrics.gauge_callback(
            "pio_quality_feedback_hit_rate",
            self._feedback_hit_rate_export,
            "Fraction of joined feedback events whose item was in the "
            "user's last served list (-1 = no joined feedback yet)",
        )
        metrics.gauge_callback(
            "pio_quality_feedback_mean_rank",
            self._feedback_mean_rank,
            "Mean served rank (1-based) of feedback items that hit",
        )

    def _make_sketch(self) -> QuantileSketch:
        """The ONE place this monitor's sketch accuracy is set: every
        window and the rank sketch must share it, or psi() rejects the
        comparison at read time."""
        return QuantileSketch(rel_err=self.config.rel_err)

    def _fresh_window(self) -> _RollingPair:
        return _RollingPair(
            self.clock, self.config.window_s, self._make_sketch
        )

    # -- intake -----------------------------------------------------------
    def observe_result(self, variant: str, payload, result) -> None:
        """One answered query from the live serving path: score
        distribution + the served-list record the feedback join reads —
        ONE lock round-trip per request (the serving hot path, same
        discipline as ingest's ``record_event``)."""
        items, scores = scores_from_result(result)
        if variant not in self._windows or not scores:
            return
        key = (
            feedback_key(payload)
            if items and any(item is not None for item in items)
            else None
        )
        with self._lock:
            snapshot_to_write = self._record_scores_locked(variant, scores)
            if key is not None:
                self._record_served_locked(key, items)
        if snapshot_to_write is not None:
            self._write_snapshot(snapshot_to_write)

    def record_scores(self, variant: str, scores: Sequence[float]) -> None:
        """Score samples for one variant (the shadow path records the
        candidate's answers here without touching the served lists —
        a shadow answer was never shown to a user)."""
        if variant not in self._windows or not scores:
            return
        with self._lock:
            snapshot_to_write = self._record_scores_locked(variant, scores)
        if snapshot_to_write is not None:
            self._write_snapshot(snapshot_to_write)

    def _record_scores_locked(
        self, variant: str, scores: Sequence[float]
    ) -> Optional[dict]:
        """Returns the baseline-pin snapshot to persist (OUTSIDE the
        lock), or None."""
        window = self._windows[variant]
        window.rotate()
        for score in scores:
            window.current.add(score)
        if (
            self._pinned is None
            and variant == _BASELINE
            # counts add across epochs — don't pay the full sketch
            # merge on every pre-pin serving call just to compare
            and window.previous.count + window.current.count
            >= self.config.pin_min_samples
        ):
            self._pinned = self._merged_locked(_BASELINE)
            return self._snapshot_locked("baseline-pin")
        return None

    def record_served(self, key: str, items: Sequence) -> None:
        with self._lock:
            self._record_served_locked(key, items)

    def _record_served_locked(self, key: str, items: Sequence) -> None:
        served = self._served
        served[key] = list(items)
        served.move_to_end(key)
        while len(served) > self.config.served_capacity:
            served.popitem(last=False)

    def record_feedback(self, key, item) -> Optional[int]:
        """Join one user-feedback event to what was served: returns the
        1-based served rank on a hit, None otherwise. Only *joinable*
        events — users present in the served LRU — count toward the
        hit-rate: an unknown user (evicted, or feedback from before this
        process served anyone — e.g. the watcher's historical backlog on
        first start) is counted as ``unjoined`` and excluded, so the
        rate measures served-list quality, not LRU coverage."""
        rank: Optional[int] = None
        joined = False
        with self._lock:
            served = self._served.get(str(key))
            if served is not None:
                joined = True
                try:
                    rank = served.index(item) + 1
                except ValueError:
                    rank = None
                self._feedback_total += 1
                if rank is not None:
                    self._feedback_hits += 1
                    self._rank_sketch.add(rank)
        outcome = "unjoined" if not joined else (
            "hit" if rank is not None else "miss"
        )
        self._feedback_events.inc(1, outcome=outcome)
        return rank

    # -- signals ----------------------------------------------------------
    def _merged_locked(self, variant: str) -> QuantileSketch:
        window = self._windows[variant]
        window.rotate()
        return window.previous.copy().merge(window.current)

    def _window_count(self, variant: str) -> int:
        with self._lock:
            return self._merged_locked(variant).count

    def score_psi(self, variant: str) -> Optional[float]:
        """PSI of ``variant``'s rolling window against the reference
        distribution: the pinned baseline snapshot when one exists, else
        (for the candidate only) the baseline's concurrent window — the
        delta-gate spirit when a pin has not formed yet. None until both
        sides hold ``min_psi_samples``."""
        if variant not in self._windows:
            return None
        with self._lock:
            current = self._merged_locked(variant)
            reference = self._pinned
            if reference is None:
                if variant == _BASELINE:
                    return None  # nothing to drift *from* yet
                reference = self._merged_locked(_BASELINE)
            if (
                current.count < self.config.min_psi_samples
                or reference.count < self.config.min_psi_samples
            ):
                return None
            return psi(reference, current)

    def psi_for_sketch(self, sketch: QuantileSketch) -> Optional[float]:
        """PSI of an externally built score sketch against the same
        reference :meth:`score_psi` uses — the continuous plane scores a
        candidate's *offline replay* distribution here before ever
        submitting it (docs/continuous.md). The sketch must be built
        with this monitor's ``config.rel_err``."""
        with self._lock:
            reference = self._pinned
            if reference is None:
                reference = self._merged_locked(_BASELINE)
            if (
                reference.count < self.config.min_psi_samples
                or sketch.count < self.config.min_psi_samples
            ):
                return None
            return psi(reference, sketch)

    def score_quantile(self, variant: str, q: float) -> float:
        if variant not in self._windows:
            return 0.0
        with self._lock:
            return self._merged_locked(variant).quantile(q)

    def feedback_hit_rate(self) -> float:
        with self._lock:
            if not self._feedback_total:
                return 0.0
            return self._feedback_hits / self._feedback_total

    def _feedback_hit_rate_export(self) -> float:
        """The /metrics view of the hit rate: -1 abstention sentinel
        while nothing has joined, same contract as the PSI gauges — an
        external alert on the raw gauge must never read 'no data' as a
        measured 0% hit rate."""
        with self._lock:
            if not self._feedback_total:
                return -1.0
            return self._feedback_hits / self._feedback_total

    def _feedback_mean_rank(self) -> float:
        with self._lock:
            return self._rank_sketch.mean()

    def pinned(self) -> bool:
        with self._lock:
            return self._pinned is not None

    def online_quality(self) -> dict:
        """The feedback-join digest the continuous controller reports as
        its online-quality number (docs/continuous.md)."""
        with self._lock:
            out = {
                "feedbackSamples": self._feedback_total,
                "hits": self._feedback_hits,
                "hitRate": (
                    round(self._feedback_hits / self._feedback_total, 4)
                    if self._feedback_total
                    else None
                ),
            }
            if self._rank_sketch.count:
                out["meanServedRank"] = round(self._rank_sketch.mean(), 3)
                out["servedRankP50"] = round(
                    self._rank_sketch.quantile(0.5), 3
                )
            return out

    # -- model lifecycle ---------------------------------------------------
    def reset_variant(self, variant: str) -> None:
        """Drop one variant's rolling window (the rollout manager calls
        this for the candidate at every rollout START: a previously
        rolled-back candidate's skewed scores must not contaminate the
        NEXT candidate's PSI for up to 2x window_s — the quarantine
        livelock the offline path already guards against)."""
        if variant not in self._windows:
            return
        with self._lock:
            self._windows[variant] = self._fresh_window()

    def model_live(self, source: str) -> None:
        """A new model went LIVE: persist the closing snapshot, drop the
        old pin and windows, and let the next ``pin_min_samples`` of
        live traffic pin the NEW baseline distribution — drift is always
        measured against the distribution of the model actually serving."""
        with self._lock:
            closing = self._snapshot_locked(f"model-live:{source}")
            self._pinned = None
            for variant in self._windows:
                self._windows[variant] = self._fresh_window()
        self._write_snapshot(closing)

    # -- snapshots ---------------------------------------------------------
    def _snapshot_locked(self, source: str) -> dict:
        serving = {}
        psi_out = {}
        for variant in self._windows:
            merged = self._merged_locked(variant)
            if merged.count:
                serving[variant] = merged.to_dict()
            reference = self._pinned
            if reference is None and variant == _CANDIDATE:
                reference = self._merged_locked(_BASELINE)
            value = (
                psi(reference, merged)
                if reference is not None
                and reference.count >= self.config.min_psi_samples
                and merged.count >= self.config.min_psi_samples
                else None
            )
            if value is not None:
                psi_out[variant] = round(value, 6)
        out: dict = {
            "schema": SNAPSHOT_SCHEMA,
            "kind": "quality",
            "source": source,
            "serving": serving,
            "psi": psi_out,
            # the deployment's configured floor rides the snapshot so
            # `pio quality --diff` abstains at the SAME bar the live
            # reads used, not a hard-coded default
            "minPsiSamples": self.config.min_psi_samples,
            "feedback": {
                "total": self._feedback_total,
                "hits": self._feedback_hits,
            },
        }
        if self._pinned is not None:
            out["pinnedBaseline"] = self._pinned.to_dict()
        return out

    def snapshot(self, source: str = "live") -> dict:
        with self._lock:
            return self._snapshot_locked(source)

    def summary(self) -> dict:
        """Small status-page / bench digest (no bucket payloads)."""
        with self._lock:
            out: dict = {
                "pinned": self._pinned is not None,
                "samples": {
                    variant: self._merged_locked(variant).count
                    for variant in self._windows
                },
            }
        out["scorePsi"] = {
            variant: (
                round(value, 6)
                if (value := self.score_psi(variant)) is not None
                else None
            )
            for variant in (_BASELINE, _CANDIDATE)
        }
        out["online"] = self.online_quality()
        return out

    def _write_snapshot(self, snap: dict) -> None:
        """Durable JSONL append (OUTSIDE the monitor lock — the fsync
        must never block a scrape or the serving path)."""
        path = self.config.snapshot_path or os.environ.get(SNAPSHOTS_ENV)
        if not path:
            return
        try:
            append_snapshot(path, snap)
        except OSError:
            pass  # evidence persistence must never fail serving


class IngestQualityMonitor:
    """Event-server-side data-quality monitor: per-app violation
    counters and event-type mix drift vs a durable baseline."""

    def __init__(
        self,
        metrics,
        clock: Callable[[], float] = time.monotonic,
        config: Optional[QualityConfig] = None,
        baseline_dir: Optional[str] = None,
    ):
        self.config = config or QualityConfig()
        self.clock = clock
        self._metrics = metrics
        self._baseline_dir = baseline_dir
        self._lock = threading.Lock()
        #: app_id -> rolling event-name count window
        self._mix: Dict[int, _RollingPair] = {}
        #: app_id -> cumulative event count (auto-pin trigger)
        self._totals: Dict[int, int] = {}
        #: app_id -> pinned {event_name: count} baseline
        self._baselines: Dict[int, Optional[Dict[str, float]]] = {}
        self._violations = metrics.counter(
            "pio_quality_ingest_violations_total",
            "Ingest data-quality violations by app and kind "
            "(schema / range / poison)",
            labelnames=("app", "kind"),
        )
        self._events = metrics.counter(
            "pio_quality_ingest_events_total",
            "Accepted events counted by the ingest quality monitor",
            labelnames=("app",),
        )

    # -- intake -----------------------------------------------------------
    def _ensure_app(self, app_id: int) -> None:
        """Lazily create the per-app window, load any durable baseline,
        and register the per-app PSI gauge (bounded by the app count —
        a closed operator-controlled set). The baseline read is disk
        I/O, so it happens OUTSIDE the monitor lock (same discipline as
        the write side) with a double-checked insert; the losing thread
        discards its read."""
        with self._lock:
            if app_id in self._mix:
                return
        loaded = self._load_baseline(app_id)
        with self._lock:
            if app_id in self._mix:
                return
            self._mix[app_id] = _RollingPair(
                self.clock, self.config.window_s, dict
            )
            self._totals[app_id] = 0
            self._baselines[app_id] = loaded
        # the registry takes its own lock; callbacks fire at collect
        # time and take the monitor lock — registering outside both
        # keeps the ordering acyclic
        self._metrics.gauge_callback(
            "pio_quality_event_mix_psi",
            (
                lambda a=app_id: (
                    p if (p := self.mix_psi(a)) is not None else -1.0
                )
            ),
            "Event-type mix PSI vs the app's pinned baseline "
            "(-1 = abstaining: no baseline yet or an empty window)",
            # pio: lint-ok[obs-unbounded-label] app ids are the operator-registered app set — closed and small; the registry's per-metric cardinality cap folds any abuse into _overflow
            labels={"app": str(app_id)},
        )

    def record_event(self, app_id: int, event) -> None:
        """One accepted event: mix accounting + value-quality checks.
        Violations are counted, never rejected here — the schema gate
        already ran; these are *quality* signals."""
        name = getattr(event, "event", None) or "?"
        violation: Optional[str] = None
        if name == "rate":
            rating = None
            props = getattr(event, "properties", None)
            if props is not None:
                try:
                    rating = props.to_dict().get("rating")
                except Exception:
                    rating = None
            if not isinstance(rating, (int, float)) or isinstance(
                rating, bool
            ) or (isinstance(rating, float) and math.isnan(rating)):
                violation = "poison"  # a rate with no usable rating
            else:
                low, high = self.config.rating_range
                if not (low <= float(rating) <= high):
                    violation = "range"
        pin: Optional[Dict[str, float]] = None
        ensured = False
        while True:
            # hot path: one lock round-trip per event — the membership
            # check rides the accounting lock; only an app's FIRST event
            # falls out to the lazy-init (disk-reading) slow path
            with self._lock:
                window = self._mix.get(app_id)
                if window is not None:
                    window.rotate()
                    counts = window.current
                    counts[name] = counts.get(name, 0) + 1
                    self._totals[app_id] += 1
                    if (
                        self._baselines.get(app_id) is None
                        and self._totals[app_id]
                        >= self.config.baseline_min_events
                    ):
                        pin = self._merged_mix_locked(app_id)
                        self._baselines[app_id] = pin
                    break
            if ensured:  # _ensure_app ran yet the window vanished: bail
                return   # rather than spin (nothing removes apps today)
            self._ensure_app(app_id)
            ensured = True
        # pio: lint-ok[obs-unbounded-label] app ids are the operator-registered app set — closed and small; the registry's cardinality cap bounds the series count regardless
        self._events.inc(1, app=str(app_id))
        if violation is not None:
            # pio: lint-ok[obs-unbounded-label] same closed per-app vocabulary as the events counter above
            self._violations.inc(1, app=str(app_id), kind=violation)
        if pin is not None:
            self._persist_baseline(app_id, pin)

    def record_rejected(self, app_id: int) -> None:
        """A 400 the schema gate produced for an authenticated app."""
        self._ensure_app(app_id)
        # pio: lint-ok[obs-unbounded-label] same closed per-app vocabulary as record_event
        self._violations.inc(1, app=str(app_id), kind="schema")

    # -- signals ----------------------------------------------------------
    def _merged_mix_locked(self, app_id: int) -> Dict[str, float]:
        window = self._mix[app_id]
        window.rotate()
        merged = dict(window.previous)
        for name, n in window.current.items():
            merged[name] = merged.get(name, 0) + n
        return merged

    def mix_psi(self, app_id: int) -> Optional[float]:
        with self._lock:
            if app_id not in self._mix:
                return None
            baseline = self._baselines.get(app_id)
            if not baseline:
                return None
            current = self._merged_mix_locked(app_id)
            return categorical_psi(baseline, current)

    def pin_baseline(self, app_id: int) -> Optional[Dict[str, float]]:
        """Explicitly (re)pin the app's mix baseline from the current
        window (operators re-baseline after an intentional mix change)."""
        with self._lock:
            if app_id not in self._mix:
                return None
            pin = self._merged_mix_locked(app_id)
            self._baselines[app_id] = pin
        self._persist_baseline(app_id, pin)
        return pin

    def summary(self) -> dict:
        with self._lock:
            apps = sorted(self._mix)
            out = {
                str(app_id): {
                    "events": self._totals.get(app_id, 0),
                    "baselinePinned": bool(self._baselines.get(app_id)),
                }
                for app_id in apps
            }
        for app_id in apps:
            value = self.mix_psi(app_id)
            if value is not None:
                out[str(app_id)]["mixPsi"] = round(value, 6)
        return out

    # -- durable baselines -------------------------------------------------
    def _baseline_path(self, app_id: int) -> Optional[str]:
        if not self._baseline_dir:
            return None
        return os.path.join(
            self._baseline_dir, f"ingest_baseline_{app_id}.json"
        )

    def _load_baseline(self, app_id: int) -> Optional[Dict[str, float]]:
        path = self._baseline_path(app_id)
        if not path:
            return None
        try:
            with open(path, encoding="utf-8") as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            return None
        if not isinstance(data, dict):
            return None
        counts = data.get("mix")
        if not isinstance(counts, dict):
            return None
        out: Dict[str, float] = {}
        for name, n in counts.items():
            try:
                out[str(name)] = float(n)
            except (TypeError, ValueError):
                continue
        return out or None

    def _persist_baseline(
        self, app_id: int, counts: Dict[str, float]
    ) -> None:
        """Durable write OUTSIDE the monitor lock (fsync discipline)."""
        path = self._baseline_path(app_id)
        if not path:
            return
        try:
            from ..utils.durability import atomic_write_bytes

            os.makedirs(os.path.dirname(path), exist_ok=True)
            atomic_write_bytes(
                path,
                json.dumps(
                    {"schema": SNAPSHOT_SCHEMA, "app": app_id,
                     "mix": counts}
                ).encode(),
            )
        except OSError:
            pass  # a read-only state dir degrades to in-memory baselines


# -- snapshot persistence / comparison ---------------------------------------


def append_snapshot(path: str, snap: dict) -> None:
    """One fsynced JSONL line (the perf ledger's append discipline)."""
    from .perfledger import append_record

    append_record(path, snap)


def load_snapshots(path: str) -> List[dict]:
    """Every parseable quality snapshot in file order; torn or foreign
    lines are skipped, never fatal."""
    out: List[dict] = []
    try:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    parsed = json.loads(line)
                except ValueError:
                    continue
                if (
                    isinstance(parsed, dict)
                    and parsed.get("kind") == "quality"
                ):
                    out.append(parsed)
    except OSError:
        return []
    return out


def snapshot_psi(
    reference: dict,
    current: dict,
    variant: str = _BASELINE,
    min_samples: int = QualityConfig.min_psi_samples,
) -> Optional[float]:
    """PSI between the same variant's serving sketch in two snapshots
    (the ``pio quality --diff`` comparison). None when either snapshot
    lacks that variant, the accuracy parameters disagree, or either
    side holds fewer than ``min_samples`` — the same floor every live
    PSI read applies: a handful-of-queries closing snapshot is sampling
    noise, not a drift verdict."""
    ref_doc = (reference.get("serving") or {}).get(variant)
    cur_doc = (current.get("serving") or {}).get(variant)
    if not isinstance(ref_doc, dict) or not isinstance(cur_doc, dict):
        return None
    try:
        ref_sketch = QuantileSketch.from_dict(ref_doc)
        cur_sketch = QuantileSketch.from_dict(cur_doc)
        if (
            ref_sketch.count < min_samples
            or cur_sketch.count < min_samples
        ):
            return None
        return psi(ref_sketch, cur_sketch)
    except (TypeError, ValueError):
        return None
