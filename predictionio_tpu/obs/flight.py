"""Always-on flight recorder + stall watchdog: post-mortem forensics.

The explaining half of the fleet-health plane (``docs/slo.md``). A
latency histogram can show *that* a server wedged; nothing before this
module could say *what the process was doing* when it did. Two pieces:

1. :class:`FlightRecorder` — a bounded ring of structured events (state
   transitions, breaker opens, rollout stage changes, promote / kill /
   gap events, alert fires) tagged with the ambient trace id. Appends
   are a single ``deque.append`` — no lock, no I/O, no formatting — so
   the recorder stays armed in production; the **disabled path is
   zero-cost** (one attribute check, the clock is never touched — the
   PR 8 profiler contract, pinned by a counting-clock test). The ring
   dumps durably on demand (``GET /blackbox.json``, ``pio blackbox``),
   on stall detection, and at process death (:func:`arm` installs
   atexit + faulthandler + optional fatal-signal hooks).
2. :class:`StallWatchdog` — detects the two wedge shapes chaos drills
   keep finding: an **in-flight request** that has outlived a multiple
   of its deadline budget, and a **subsystem tick** (continuous
   controller, feed watcher, replica tailer) that stopped beating. A
   new stall increments ``pio_stall_detected_total{site}``, records a
   flight event, and dumps the ring next to the evidence ledgers —
   the post-mortem exists *before* anyone starts debugging.

Stdlib-only and device-free, importable from every server path.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from .trace import current_context

__all__ = [
    "FLIGHT_ENV",
    "FLIGHT_DIR_ENV",
    "FlightRecorder",
    "StallWatchdog",
    "arm",
    "default_recorder",
    "load_dump",
    "record",
    "write_dump",
]

#: set to "0" to disable the process flight recorder entirely
FLIGHT_ENV = "PIO_FLIGHT"

#: directory crash/stall dumps land in (unset = no durable dumps)
FLIGHT_DIR_ENV = "PIO_FLIGHT_DIR"

#: ring capacity — one screenful of history per subsystem at typical
#: transition rates, bounded regardless of uptime
DEFAULT_CAPACITY = 2048

DUMP_SCHEMA = 1


def _env_enabled() -> bool:
    return os.environ.get(FLIGHT_ENV, "1") != "0"


class FlightRecorder:
    """Bounded append-only ring of structured events.

    ``record`` relies on ``deque.append`` with a ``maxlen`` being atomic
    under the GIL — the hot path takes no lock, so an event from inside
    a breaker transition (recorded while the breaker's own lock is
    held) can never deadlock against a concurrent dump. ``dump`` reads
    a snapshot copy.
    """

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        enabled: Optional[bool] = None,
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
    ):
        self.enabled = _env_enabled() if enabled is None else enabled
        self.clock = clock
        self.wall = wall
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._dropped = 0  # approximate: ring length is the honest bound

    def record(self, kind: str, site: str, **details) -> None:
        """Append one event. Disabled, this is ONE attribute check and a
        return — no clock read, no allocation beyond the call frame."""
        if not self.enabled:
            return
        ctx = current_context()
        self._ring.append(
            {
                "t": self.clock(),
                "wall": self.wall(),
                "kind": kind,
                "site": site,
                "trace": ctx.trace_id if ctx is not None else None,
                "details": details or None,
            }
        )

    def dump(self) -> List[dict]:
        """Snapshot of the ring, oldest first."""
        return list(self._ring)

    def clear(self) -> None:
        self._ring.clear()

    def __len__(self) -> int:
        return len(self._ring)

    def dump_to(self, path: str, reason: str = "on-demand") -> str:
        """Durable dump of the ring (see :func:`write_dump`)."""
        return write_dump(path, self.dump(), reason, at=self.wall())


def write_dump(
    path: str, events, reason: str, at: Optional[float] = None
) -> str:
    """THE flight-dump file format — header line + one JSONL line per
    event, fsynced (the evidence-ledger discipline: a dump a crash can
    tear is not a flight recorder). One owner: the recorder's own
    dumps, the watchdog's stall dumps and ``pio blackbox dump --out``
    all write through here, so the schema can never fork."""
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(
            json.dumps(
                {
                    "schema": DUMP_SCHEMA,
                    "kind": "flight-dump",
                    "reason": reason,
                    "pid": os.getpid(),
                    "events": len(events),
                    "at": time.time() if at is None else at,
                },
                sort_keys=True,
            )
            + "\n"
        )
        for event in events:
            fh.write(json.dumps(event, sort_keys=True, default=str) + "\n")
        fh.flush()
        os.fsync(fh.fileno())
    return path


def load_dump(path: str) -> Optional[dict]:
    """A dump file → ``{"header": ..., "events": [...]}``; torn lines
    are skipped, a missing/foreign file is None, never a traceback."""
    header: Optional[dict] = None
    events: List[dict] = []
    try:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    parsed = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(parsed, dict):
                    continue
                if parsed.get("kind") == "flight-dump":
                    header = parsed
                else:
                    events.append(parsed)
    except OSError:
        return None
    if header is None and not events:
        return None
    return {"header": header or {}, "events": events}


# -- process-wide default ------------------------------------------------------

_default_lock = threading.Lock()
_default: Optional[FlightRecorder] = None


def default_recorder() -> FlightRecorder:
    """The process flight recorder: every subsystem records into one
    ring, so a dump interleaves breaker opens, rollout transitions and
    alert fires on one timeline — which is the whole point."""
    global _default
    with _default_lock:
        if _default is None:
            _default = FlightRecorder()
        return _default


def record(kind: str, site: str, **details) -> None:
    """Record into the process recorder (the convenience every tap
    uses; a recorder fault must never take down the recording site)."""
    try:
        default_recorder().record(kind, site, **details)
    except Exception:
        pass


_armed = False


def arm(
    dump_dir: Optional[str] = None, signals: bool = False
) -> Optional[str]:
    """Arm the crash path: an atexit dump of the process recorder into
    ``dump_dir`` (default ``PIO_FLIGHT_DIR``; None = disarmed) plus
    ``faulthandler`` into ``<dir>/faulthandler-<pid>.txt`` so a hard
    crash leaves both the interpreter stacks and the event timeline.
    ``signals=True`` additionally dumps on SIGTERM before re-raising
    the default action — only the server CLIs set it (a library import
    must never steal signal dispositions). Idempotent."""
    global _armed
    directory = dump_dir or os.environ.get(FLIGHT_DIR_ENV)
    if not directory:
        return None
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"flight-{os.getpid()}.jsonl")
    with _default_lock:
        if _armed:
            return path
        _armed = True
    import atexit

    recorder = default_recorder()
    atexit.register(
        lambda: _safe_dump(recorder, path, "atexit")
    )
    try:
        import faulthandler

        fh_path = os.path.join(
            directory, f"faulthandler-{os.getpid()}.txt"
        )
        _fh_file = open(fh_path, "w")  # held open for process lifetime
        faulthandler.enable(file=_fh_file)
    except (OSError, RuntimeError):
        pass
    if signals:
        import signal as _signal

        def on_term(signum, frame):
            _safe_dump(recorder, path, f"signal-{signum}")
            _signal.signal(signum, _signal.SIG_DFL)
            _signal.raise_signal(signum)

        try:
            _signal.signal(_signal.SIGTERM, on_term)
        except (ValueError, OSError):
            pass  # non-main thread / platform without SIGTERM
    return path


def _safe_dump(recorder: FlightRecorder, path: str, reason: str) -> None:
    try:
        recorder.dump_to(path, reason=reason)
    except Exception:
        pass


# -- stall watchdog -----------------------------------------------------------

#: default budget for a tracked request that carries no deadline
DEFAULT_BUDGET_S = 10.0


class StallWatchdog:
    """Detects wedged requests and wedged subsystem ticks.

    Request path: :meth:`enter`/:meth:`exit` bracket each in-flight
    request with its deadline budget; a request still in flight after
    ``stall_factor x budget`` is a stall. Subsystem path: loops declare
    themselves with :meth:`expect` and call :meth:`beat` every
    iteration; a beat older than the declared gap is a stall.

    :meth:`check` (called by the health ticker, or directly by drills
    on injected clocks) fires each NEW stall once — counter + flight
    event + a durable ring dump naming the site — and records recovery
    when the condition goes away, so a transient wedge leaves a
    complete fire/recover timeline."""

    def __init__(
        self,
        metrics,
        clock: Callable[[], float] = time.monotonic,
        flight: Optional[FlightRecorder] = None,
        stall_factor: float = 4.0,
        min_stall_s: float = 1.0,
        dump_dir: Optional[str] = None,
    ):
        self.clock = clock
        self.flight = flight
        self.stall_factor = stall_factor
        self.min_stall_s = min_stall_s
        self._dump_dir = dump_dir
        self._lock = threading.Lock()
        self._inflight: Dict[int, tuple] = {}  # token -> (site, t0, budget)
        self._next_token = 0
        self._beats: Dict[str, float] = {}
        self._expected: Dict[str, float] = {}  # site -> max gap
        self._flagged: Dict[str, float] = {}  # site -> stall-detected t
        self._stalls_total = 0
        self._last_dump: Optional[str] = None
        self._stalls = metrics.counter(
            "pio_stall_detected_total",
            "Stalls detected by the watchdog, by site",
            labelnames=("site",),
        )
        metrics.gauge_callback(
            "pio_stall_inflight",
            self._inflight_count,
            "Requests currently tracked by the stall watchdog",
        )

    # -- request tracking --------------------------------------------------
    def enter(self, site: str, budget_s: Optional[float] = None) -> int:
        with self._lock:
            self._next_token += 1
            token = self._next_token
            self._inflight[token] = (
                site,
                self.clock(),
                budget_s if budget_s and budget_s > 0 else DEFAULT_BUDGET_S,
            )
            return token

    def exit(self, token: int) -> None:
        with self._lock:
            self._inflight.pop(token, None)

    def _inflight_count(self) -> int:
        with self._lock:
            return len(self._inflight)

    # -- subsystem heartbeats ----------------------------------------------
    def expect(self, site: str, max_gap_s: float) -> None:
        """Declare a watched loop; the declaration time counts as the
        first beat (a loop that never runs at all must still stall)."""
        with self._lock:
            self._expected[site] = max_gap_s
            self._beats.setdefault(site, self.clock())

    def unexpect(self, site: str) -> None:
        with self._lock:
            self._expected.pop(site, None)
            self._beats.pop(site, None)
            self._flagged.pop(site, None)

    def beat(self, site: str) -> None:
        with self._lock:
            self._beats[site] = self.clock()

    # -- detection ---------------------------------------------------------
    def check(self) -> List[dict]:
        """One detection round; returns the stalls NEWLY fired."""
        now = self.clock()
        fired: List[dict] = []
        with self._lock:
            stalled_sites: Dict[str, dict] = {}
            for site, t0, budget in self._inflight.values():
                bar = max(self.min_stall_s, self.stall_factor * budget)
                elapsed = now - t0
                if elapsed > bar:
                    info = stalled_sites.setdefault(
                        site,
                        {"site": site, "stallKind": "request",
                         "worstElapsedS": 0.0, "count": 0},
                    )
                    info["count"] += 1
                    info["worstElapsedS"] = max(
                        info["worstElapsedS"], round(elapsed, 3)
                    )
            for site, max_gap in self._expected.items():
                age = now - self._beats.get(site, now)
                if age > max_gap:
                    stalled_sites[site] = {
                        "site": site, "stallKind": "tick",
                        "beatAgeS": round(age, 3),
                        "maxGapS": max_gap,
                    }
            new = [
                info
                for site, info in stalled_sites.items()
                if site not in self._flagged
            ]
            for info in new:
                self._flagged[info["site"]] = now
                self._stalls_total += 1
            recovered = [
                site for site in self._flagged if site not in stalled_sites
            ]
            for site in recovered:
                del self._flagged[site]
        for info in new:
            fired.append(info)
            # site is a closed code-defined vocabulary (serving.request,
            # continuous.tick, replica.tail, ...), never request data
            self._stalls.inc(1, site=info["site"])
            if self.flight is not None:
                self.flight.record("stall", info["site"], **{
                    k: v for k, v in info.items() if k != "site"
                })
                self._dump_for(info["site"])
        for site in recovered:
            if self.flight is not None:
                self.flight.record("stall-recovered", site)
        return fired

    def _dump_for(self, site: str) -> None:
        directory = self._dump_dir or os.environ.get(FLIGHT_DIR_ENV)
        if not directory or self.flight is None:
            return
        safe = "".join(
            c if c.isalnum() or c in "._-" else "_" for c in site
        )
        path = os.path.join(
            directory, f"stall-{safe}-{os.getpid()}.jsonl"
        )
        try:
            self.flight.dump_to(path, reason=f"stall:{site}")
            self._last_dump = path
        except OSError:
            pass  # a read-only dir degrades to in-memory forensics

    # -- reporting ---------------------------------------------------------
    def summary(self) -> dict:
        with self._lock:
            return {
                "detected": self._stalls_total,
                "active": sorted(self._flagged),
                "inflight": len(self._inflight),
                "watched": sorted(self._expected),
                "lastDump": self._last_dump,
            }
