"""Mergeable streaming quantile sketches + population stability index.

The data model of the model-quality observability plane
(``docs/observability.md#quality``). A served-score distribution at
millions of QPS cannot be kept as samples; it CAN be kept as a
log-bucketed sketch — bounded memory, mergeable across servers and
across time windows, and accurate to a *relative* error bound that holds
across the four-plus orders of magnitude a recommender's scores span.

- :class:`QuantileSketch` — a DDSketch-shaped store: geometric buckets
  (``gamma = (1 + rel_err) / (1 - rel_err)``) over positive and negative
  magnitudes plus a zero bucket. ``quantile(q)`` is within ``rel_err``
  relative error of the exact sample quantile for every value whose
  magnitude exceeds ``min_magnitude`` (the documented bound the golden
  tests pin against ``numpy.quantile``). ``merge`` is bucket-wise
  addition — associative and lossless, the property that lets per-window
  and per-variant sketches combine without re-reading any sample.
- :func:`psi` — population stability index between two sketches over the
  union of their buckets, the standard distribution-drift score
  (identical distributions → ~0; a real shift → large). Empty-bucket
  probabilities are floored at ``epsilon`` so a bucket present on one
  side only contributes a finite, bounded term.
- :func:`categorical_psi` — the same index over two categorical count
  maps (the event-type *mix* drift signal at the ingest plane).

This module mirrors the ``metrics.py`` histogram's log-scale bucket
philosophy (constant relative error at fixed series count) but keys
buckets by integer index instead of a fixed bound tuple, because a
drift sketch must cover scores it has never seen — a fixed bound list
chosen at startup would clamp exactly the outliers drift detection
exists to notice. Like ``metrics.py`` and ``rollout/plan.py`` it is
stdlib-only and device-free, with no clocks at all — windowing lives in
:mod:`predictionio_tpu.obs.quality`, where the clock is injected.
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, Mapping, Optional, Tuple

__all__ = [
    "DEFAULT_MAX_BUCKETS",
    "DEFAULT_MIN_MAGNITUDE",
    "DEFAULT_REL_ERR",
    "PSI_COARSEN",
    "QuantileSketch",
    "categorical_psi",
    "psi",
]

#: default relative accuracy of ``quantile()`` — 2% keeps ~512 buckets
#: good for ~8 decades of dynamic range per sign
DEFAULT_REL_ERR = 0.02

#: magnitudes at or below this collapse into the zero bucket (a relative
#: bound is meaningless at 1e-300, and the index would overflow anyway)
DEFAULT_MIN_MAGNITUDE = 1e-9

#: hard cap on stored buckets per sign; past it the lowest-magnitude
#: buckets collapse downward (the tail the quantiles care about is the
#: HIGH-magnitude end, so accuracy degrades only near zero)
DEFAULT_MAX_BUCKETS = 512

#: saturation value for the running sum: clamped extremes can still
#: overflow float addition, and an inf sum would both poison mean()
#: and serialize as a non-RFC "Infinity" token in the snapshot JSONL
_MAX_FLOAT = 1.7976931348623157e308

#: probability floor for PSI terms: a bucket empty on one side must
#: contribute a finite term, not an infinite log-ratio
PSI_EPSILON = 1e-4

#: sketch buckets per PSI bin. PSI over the raw 2%-relative buckets is
#: inflated by sampling noise: with a few hundred samples spread over
#: ~50 occupied buckets, the epsilon floor turns every
#: present-on-one-side-only bucket into a spurious term (a 120-sample
#: same-distribution resample reads ~0.6 — past the 0.25 "real change"
#: bar with zero actual drift). Grouping ``coarsen`` adjacent buckets
#: per bin (gamma^16 ≈ 1.9× per bin: roughly binary-magnitude bins, the
#: conventional 10–20 PSI bins over a typical score range) drops that
#: same resample to ~0.05 while a genuine 1.5× scale shift still reads
#: >0.4 — the separation the gate needs at its sample floor.
PSI_COARSEN = 16


class QuantileSketch:
    """Log-bucketed streaming quantile sketch (DDSketch-style).

    Values land in geometric buckets: positive ``v`` goes to bucket
    ``ceil(log_gamma(v))``, negative values mirror into a separate
    store, and ``|v| <= min_magnitude`` counts in the zero bucket.
    Memory is bounded by ``max_buckets`` per sign; ``count``/``sum``/
    ``min``/``max`` ride along exactly.
    """

    __slots__ = (
        "rel_err",
        "min_magnitude",
        "max_buckets",
        "_log_gamma",
        "_top_index",
        "_top_value",
        "_pos",
        "_neg",
        "_zero",
        "count",
        "sum",
        "min",
        "max",
    )

    def __init__(
        self,
        rel_err: float = DEFAULT_REL_ERR,
        min_magnitude: float = DEFAULT_MIN_MAGNITUDE,
        max_buckets: int = DEFAULT_MAX_BUCKETS,
    ):
        if not (0.0 < rel_err < 1.0):
            raise ValueError(f"rel_err must be in (0, 1), got {rel_err!r}")
        if min_magnitude <= 0.0:
            raise ValueError("min_magnitude must be positive")
        if max_buckets < 8:
            raise ValueError("max_buckets must be at least 8")
        self.rel_err = rel_err
        self.min_magnitude = min_magnitude
        self.max_buckets = max_buckets
        gamma = (1.0 + rel_err) / (1.0 - rel_err)
        self._log_gamma = math.log(gamma)
        #: largest index any value may land in: the bucket of
        #: max-float/2. max-float's own bucket rounds UP past it, and
        #: _bucket_value of that index overflows on read — so both
        #: infinities and near-max finite magnitudes clamp here
        self._top_index = self._index(8.988465674311579e307)
        #: intake magnitude cap: the top bucket's representative value —
        #: precomputed, add() is the serving hot path
        self._top_value = self._bucket_value(self._top_index)
        self._pos: Dict[int, int] = {}
        self._neg: Dict[int, int] = {}
        self._zero = 0
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    # -- intake -----------------------------------------------------------
    def _index(self, magnitude: float) -> int:
        return math.ceil(math.log(magnitude) / self._log_gamma)

    def _bucket_value(self, index: int) -> float:
        """Representative value of bucket ``index``: the geometric
        midpoint of ``(gamma^(i-1), gamma^i]``, which is what bounds the
        relative error at ``rel_err``. The exponent is capped so an
        out-of-range index from a hand-edited snapshot reads as a huge
        finite value instead of raising OverflowError."""
        gamma = math.exp(self._log_gamma)
        exp_arg = min(self._log_gamma * index, 709.0)
        return (2.0 * math.exp(exp_arg)) / (gamma + 1.0)

    def add(self, value: float, count: int = 1) -> None:
        value = float(value)
        if math.isnan(value) or count <= 0:
            return  # a NaN score is a data bug, not a distribution sample
        if math.isinf(value) or abs(value) > self._top_value:
            # an overflowing score (inf OR near-max finite) must rank as
            # the extreme of the distribution, never as its minimum —
            # and sum/min/max take the clamped stand-in too, or one such
            # score poisons mean() forever and json.dumps writes a
            # non-RFC "Infinity" token into the durable snapshot line
            value = math.copysign(self._top_value, value)
        self.count += count
        self.sum += value * count
        if math.isinf(self.sum):
            # a few clamped extremes can still overflow the running sum
            self.sum = math.copysign(_MAX_FLOAT, self.sum)
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        magnitude = abs(value)
        if magnitude <= self.min_magnitude:
            self._zero += count
            return
        store = self._pos if value > 0 else self._neg
        idx = min(self._index(magnitude), self._top_index)
        store[idx] = store.get(idx, 0) + count
        if len(store) > self.max_buckets:
            self._collapse(store)

    @staticmethod
    def _collapse(store: Dict[int, int]) -> None:
        """Fold the lowest-index (smallest-magnitude) bucket into its
        neighbor — bounded memory at the cost of accuracy near zero,
        never at the tail."""
        low = sorted(store)
        first, second = low[0], low[1]
        store[second] = store.get(second, 0) + store.pop(first)

    def extend(self, values: Iterable[float]) -> None:
        for value in values:
            self.add(value)

    # -- queries ----------------------------------------------------------
    def quantile(self, q: float) -> float:
        """The ``q`` (0..1) quantile, within ``rel_err`` relative error
        for values with ``|v| > min_magnitude``. 0.0 when empty."""
        if not (0.0 <= q <= 1.0):
            raise ValueError(f"q must be in [0, 1], got {q!r}")
        if self.count <= 0:
            return 0.0
        rank = q * (self.count - 1)
        # walk: most-negative first (negative store, descending index),
        # then zero, then positive ascending
        seen = 0
        for idx in sorted(self._neg, reverse=True):
            seen += self._neg[idx]
            if seen > rank:
                return -self._bucket_value(idx)
        seen += self._zero
        if seen > rank:
            return 0.0
        for idx in sorted(self._pos):
            seen += self._pos[idx]
            if seen > rank:
                return self._bucket_value(idx)
        return self.max if self.count else 0.0

    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    # -- merge / serialization --------------------------------------------
    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Bucket-wise add ``other`` into ``self`` (in place; returns
        self). Requires identical accuracy parameters — merging sketches
        with different gammas would silently mis-bin every count."""
        if (
            other.rel_err != self.rel_err
            or other.min_magnitude != self.min_magnitude
        ):
            raise ValueError(
                "cannot merge sketches with different accuracy parameters "
                f"(rel_err {self.rel_err} vs {other.rel_err})"
            )
        for idx, n in other._pos.items():
            self._pos[idx] = self._pos.get(idx, 0) + n
        for idx, n in other._neg.items():
            self._neg[idx] = self._neg.get(idx, 0) + n
        while len(self._pos) > self.max_buckets:
            self._collapse(self._pos)
        while len(self._neg) > self.max_buckets:
            self._collapse(self._neg)
        self._zero += other._zero
        self.count += other.count
        self.sum += other.sum
        if math.isinf(self.sum):
            self.sum = math.copysign(_MAX_FLOAT, self.sum)
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        return self

    def copy(self) -> "QuantileSketch":
        out = QuantileSketch(self.rel_err, self.min_magnitude, self.max_buckets)
        out.merge(self)
        return out

    def to_dict(self) -> dict:
        """JSON-shaped snapshot (string bucket keys: JSON object keys)."""
        out: dict = {
            "relErr": self.rel_err,
            "minMagnitude": self.min_magnitude,
            "count": self.count,
            "sum": self.sum,
            "zero": self._zero,
            "pos": {str(k): v for k, v in self._pos.items()},
            "neg": {str(k): v for k, v in self._neg.items()},
        }
        if self.count:
            out["min"] = self.min
            out["max"] = self.max
        return out

    @classmethod
    def from_dict(cls, data: Mapping) -> "QuantileSketch":
        """Inverse of :meth:`to_dict`; unparseable bucket entries are
        skipped (a hand-edited snapshot line must not crash a report)."""
        out = cls(
            rel_err=float(data.get("relErr", DEFAULT_REL_ERR)),
            min_magnitude=float(
                data.get("minMagnitude", DEFAULT_MIN_MAGNITUDE)
            ),
        )
        for attr, key in (("_pos", "pos"), ("_neg", "neg")):
            store = getattr(out, attr)
            for raw_idx, n in (data.get(key) or {}).items():
                try:
                    store[int(raw_idx)] = int(n)
                except (TypeError, ValueError):
                    continue
        out._zero = int(data.get("zero", 0) or 0)
        out.count = int(data.get("count", 0) or 0)
        out.sum = float(data.get("sum", 0.0) or 0.0)
        out.min = float(data.get("min", math.inf))
        out.max = float(data.get("max", -math.inf))
        return out

    def _distribution(
        self, coarsen: int = 1
    ) -> Dict[Tuple[str, int], float]:
        """PSI-bin key → probability: ``coarsen`` adjacent sketch buckets
        fold into one bin (floor division keeps the mapping consistent
        for negative indices)."""
        if self.count <= 0:
            return {}
        total = float(self.count)
        out: Dict[Tuple[str, int], float] = {}
        for sign, store in (("p", self._pos), ("n", self._neg)):
            for idx, n in store.items():
                key = (sign, idx // coarsen)
                out[key] = out.get(key, 0.0) + n / total
        if self._zero:
            out[("z", 0)] = self._zero / total
        return out


def _psi_terms(
    reference: Mapping, current: Mapping, epsilon: float
) -> float:
    total = 0.0
    for key in set(reference) | set(current):
        p = max(float(reference.get(key, 0.0)), epsilon)
        q = max(float(current.get(key, 0.0)), epsilon)
        total += (p - q) * math.log(p / q)
    return total


def psi(
    reference: QuantileSketch,
    current: QuantileSketch,
    epsilon: float = PSI_EPSILON,
    coarsen: int = PSI_COARSEN,
) -> Optional[float]:
    """Population stability index between two sketches' distributions,
    computed over ``coarsen``-bucket PSI bins (see :data:`PSI_COARSEN` —
    the raw 2%-relative buckets are too fine for small samples). ~0 for
    identical distributions; conventional thresholds read <0.1 as
    stable, 0.1–0.25 as moderate shift, >0.25 as a real distribution
    change. None when either side is empty — "no data" is an
    abstention, not zero drift."""
    if reference.count <= 0 or current.count <= 0:
        return None
    if (
        reference.rel_err != current.rel_err
        or reference.min_magnitude != current.min_magnitude
    ):
        raise ValueError(
            "PSI requires sketches with identical accuracy parameters"
        )
    if coarsen < 1:
        raise ValueError(f"coarsen must be >= 1, got {coarsen!r}")
    return _psi_terms(
        reference._distribution(coarsen),
        current._distribution(coarsen),
        epsilon,
    )


def categorical_psi(
    reference: Mapping[str, float],
    current: Mapping[str, float],
    epsilon: float = PSI_EPSILON,
) -> Optional[float]:
    """PSI over two categorical count maps (e.g. event-name → count):
    the *mix* drift signal. Counts are normalized here; None when either
    side has no mass."""
    ref_total = float(sum(reference.values())) if reference else 0.0
    cur_total = float(sum(current.values())) if current else 0.0
    if ref_total <= 0 or cur_total <= 0:
        return None
    return _psi_terms(
        {k: v / ref_total for k, v in reference.items() if v > 0},
        {k: v / cur_total for k, v in current.items() if v > 0},
        epsilon,
    )
