"""SLO engine: declarative objectives, burn-rate alerting, alert ledger.

The acting half of the fleet-health plane (``docs/slo.md``). PRs 4/8/10
made the system measurable — metric families for availability, latency,
freshness and drift — but nothing *acts* on those signals except the
rollout gates. This module closes that gap with the classic SRE shape:

- an :class:`SLOObjective` is a declarative statement over an *existing*
  metric family ("99.9% of responses are non-5xx", "99% of queries
  answer under 512 ms", "feed lag stays under 5000 ops", "score PSI
  stays under 0.25");
- the :class:`SLOEngine` evaluates every objective with **multi-window
  burn-rate logic** (a fast ~5 m window for detection speed and a slow
  ~1 h window for confidence, both on injected clocks): an alert fires
  only when *both* windows burn error budget faster than the
  objective's threshold, and clears when the fast window is back inside
  budget — the Google-SRE pattern that pages on real incidents and
  sleeps through blips;
- every FIRING/CLEARED transition is appended durably to a
  schema-versioned, fsynced JSONL **alert ledger** (the perf ledger's
  append discipline: torn lines are skipped on load, the file is
  evidence, not a cache), and mirrored onto ``/metrics``
  (``pio_slo_alert_state{objective}``) and the flight recorder.

**Abstention is explicit** (PR 10's "no data is never a verdict"
contract): an objective whose source series is absent — or whose gauge
exports the ``-1`` abstention sentinel, or whose window holds fewer than
``min_window_events`` observations — reports ``abstaining`` and neither
fires nor clears. A firing alert does NOT clear on data loss.

Stdlib-only and device-free like the rest of ``obs`` — the engine reads
the in-process :class:`~predictionio_tpu.obs.metrics.MetricsRegistry`
directly, so every server type carries one with zero scrape
infrastructure.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .metrics import Counter, Gauge, Histogram, MetricsRegistry

__all__ = [
    "ALERT_SCHEMA",
    "ALERT_LEDGER_ENV",
    "HealthConfig",
    "HealthPlane",
    "SLOEngine",
    "SLOObjective",
    "default_objectives",
    "load_alerts",
]

ALERT_SCHEMA = 1

#: env naming the JSONL file alert transitions append to (the alerting
#: twin of ``PIO_PERF_LEDGER`` / ``PIO_QUALITY_SNAPSHOTS``)
ALERT_LEDGER_ENV = "PIO_ALERT_LEDGER"

#: env setting the background evaluation cadence (seconds; 0 disables
#: the thread — evaluation then only happens on explicit tick() calls)
TICK_ENV = "PIO_SLO_TICK_S"

DEFAULT_TICK_S = 15.0

_OK = "OK"
_FIRING = "FIRING"


@dataclasses.dataclass(frozen=True)
class SLOObjective:
    """One declarative objective over an existing metric family.

    Two evaluation kinds:

    - ``ratio`` — good/bad event counts from a cumulative family:
      a *status counter* (``metric`` = a counter with a ``status``
      label; ``bad_status_min`` and up are bad) or a *latency
      histogram* (``latency_threshold_s`` set; observations at or under
      the threshold are good). Burn rate over a window =
      ``bad_fraction / (1 - target)`` — 1.0 means the error budget is
      being spent exactly at the sustainable rate.
    - ``gauge`` — a current-value family (feed lag, PSI): burn rate =
      ``window_mean / max_value``; negative samples are the metrics
      plane's abstention sentinel and read as *absent*, never as zero.

    An alert fires when BOTH windows burn at ``burn_threshold`` or
    faster, and clears when the fast window drops below
    ``clear_threshold``.
    """

    name: str
    kind: str  # "ratio" | "gauge"
    metric: str
    #: ratio: target good fraction (error budget = 1 - target)
    target: float = 0.999
    #: ratio over a histogram: observations <= this bound are good
    #: (align with a bucket bound; DEFAULT_BUCKETS are 0.0005 * 2^i)
    latency_threshold_s: Optional[float] = None
    #: ratio over a status counter: statuses >= this are bad
    bad_status_min: int = 500
    #: gauge: the value at which burn rate reads 1.0
    max_value: Optional[float] = None
    #: label filter applied to the source series (e.g. variant=baseline)
    labels: Tuple[Tuple[str, str], ...] = ()
    #: gauge only: evaluate an INDEPENDENT burn/alert state machine per
    #: distinct value of this label (e.g. ``partition`` on
    #: ``pio_replication_lag_ops``, docs/storage.md#partitioning) —
    #: each series fires/clears alone, named ``<name>[<value>]``, so
    #: one lagging partition can never hide behind a healthy mean (or
    #: behind the fleet's worst-of collapse losing WHICH one is sick)
    per_label: Optional[str] = None
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    burn_threshold: float = 8.0
    clear_threshold: float = 1.0
    #: ratio: a window with fewer total events than this abstains — a
    #: single 500 in a 3-request window is sampling noise, not a burn
    min_window_events: int = 10

    def __post_init__(self):
        if self.kind not in ("ratio", "gauge"):
            raise ValueError(f"unknown SLO kind {self.kind!r}")
        if self.kind == "ratio" and not (0.0 < self.target < 1.0):
            raise ValueError(f"{self.name}: target must be in (0, 1)")
        if self.kind == "gauge" and not self.max_value:
            raise ValueError(f"{self.name}: gauge objectives need max_value")
        if self.per_label and self.kind != "gauge":
            raise ValueError(
                f"{self.name}: per_label evaluation is gauge-only"
            )


def default_objectives(kind: str) -> Tuple[SLOObjective, ...]:
    """The stock objective set for one server kind (docs/slo.md). Every
    objective reads a family the server may not export — absence is
    abstention, so one shared availability objective is safe on all of
    them while freshness/drift only ever report where the plane exists."""
    availability = SLOObjective(
        name="availability", kind="ratio", metric="pio_http_responses_total",
        target=0.999,
    )
    if kind == "query":
        return (
            availability,
            SLOObjective(
                name="latency", kind="ratio",
                metric="pio_serving_request_seconds",
                latency_threshold_s=0.512, target=0.99,
            ),
            SLOObjective(
                name="freshness", kind="gauge",
                metric="pio_continuous_feed_lag_ops",
                max_value=5000.0, burn_threshold=1.0,
            ),
            SLOObjective(
                name="drift", kind="gauge",
                metric="pio_quality_score_psi",
                labels=(("variant", "baseline"),),
                max_value=0.25, burn_threshold=1.0,
            ),
        )
    if kind == "router":
        return (
            availability,
            SLOObjective(
                name="latency", kind="ratio",
                metric="pio_router_request_seconds",
                latency_threshold_s=0.512, target=0.99,
            ),
        )
    if kind == "event":
        return (
            availability,
            SLOObjective(
                name="latency", kind="ratio",
                metric="pio_http_request_seconds",
                latency_threshold_s=0.128, target=0.99,
            ),
            SLOObjective(
                name="drift", kind="gauge",
                metric="pio_quality_event_mix_psi",
                max_value=0.25, burn_threshold=1.0,
            ),
        )
    if kind == "storage":
        return (
            availability,
            SLOObjective(
                name="latency", kind="ratio",
                metric="pio_storage_op_seconds",
                latency_threshold_s=0.128, target=0.99,
            ),
            SLOObjective(
                name="freshness", kind="gauge",
                metric="pio_replication_lag_ops",
                max_value=10000.0, burn_threshold=1.0,
                # one alert state machine PER PARTITION slot: a single
                # lagging chain fires freshness[<i>] on its own, never
                # averaged against healthy siblings
                # (docs/storage.md#partitioning)
                per_label="partition",
            ),
        )
    # dashboard and anything future: availability is universal
    return (availability,)


# -- alert ledger -------------------------------------------------------------


def load_alerts(path: str) -> List[dict]:
    """Every parseable alert record in file order; torn or foreign lines
    are skipped, never fatal (the perf-ledger load discipline)."""
    import json

    out: List[dict] = []
    try:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    parsed = json.loads(line)
                except ValueError:
                    continue
                if isinstance(parsed, dict) and parsed.get("kind") == "alert":
                    out.append(parsed)
    except OSError:
        return []
    return out


# -- windowed series ----------------------------------------------------------


class _Series:
    """Bounded ring of timestamped samples for one objective. Ratio
    objectives store cumulative ``(t, good, bad)``; gauge objectives
    store ``(t, value)``. NOT thread-safe — the engine's lock guards it."""

    #: hard cap on retained samples (a 1 s tick against a 1 h window
    #: would otherwise grow without bound)
    MAX_SAMPLES = 4096

    def __init__(self):
        self.samples: List[tuple] = []

    def add(self, sample: tuple, keep_window_s: float) -> None:
        self.samples.append(sample)
        cutoff = sample[0] - keep_window_s
        # prune from the head, keep one sample AT/BEFORE the cutoff so a
        # full slow window always has a baseline point to delta against
        while len(self.samples) > 2 and self.samples[1][0] <= cutoff:
            self.samples.pop(0)
        if len(self.samples) > self.MAX_SAMPLES:
            self.samples.pop(0)

    def ratio_window(
        self, now: float, window_s: float
    ) -> Optional[Tuple[float, float]]:
        """``(delta_good, delta_bad)`` between the newest sample and the
        newest sample at least ``window_s`` old (or the oldest sample —
        a partial window is still evidence). None with <2 samples."""
        if len(self.samples) < 2:
            return None
        newest = self.samples[-1]
        cutoff = now - window_s
        base = self.samples[0]
        for sample in self.samples:
            if sample[0] <= cutoff:
                base = sample
            else:
                break
        if base is newest:
            base = self.samples[-2]
        dgood = newest[1] - base[1]
        dbad = newest[2] - base[2]
        if dgood < 0 or dbad < 0:  # a counter reset (restart): no verdict
            return None
        return (dgood, dbad)

    def gauge_window(self, now: float, window_s: float) -> Optional[float]:
        """Mean of the samples inside the window (the newest always
        counts). None when no samples exist."""
        if not self.samples:
            return None
        cutoff = now - window_s
        values = [s[1] for s in self.samples if s[0] > cutoff]
        if not values:
            values = [self.samples[-1][1]]
        return sum(values) / len(values)


# -- readers over the in-process registry ------------------------------------


def _match(labels: Dict[str, str], want: Tuple[Tuple[str, str], ...]) -> bool:
    return all(labels.get(k) == v for k, v in want)


def _read_ratio(
    metrics: MetricsRegistry, obj: SLOObjective
) -> Optional[Tuple[float, float]]:
    """Cumulative ``(good, bad)`` for a ratio objective, or None when
    the source family does not exist yet."""
    inst = metrics.instrument(obj.metric)
    if inst is None:
        return None
    if obj.latency_threshold_s is not None:
        if not isinstance(inst, Histogram):
            return None
        good = 0.0
        total = 0.0
        threshold = obj.latency_threshold_s * (1.0 + 1e-9)
        for labels, snap in inst.label_snapshots():
            if not _match(labels, obj.labels):
                continue
            cumulative = snap["buckets"]
            total += cumulative[-1][1]
            under = 0
            for bound, count in cumulative:
                if bound <= threshold:
                    under = count
                else:
                    break
            good += under
        return (good, total - good)
    if not isinstance(inst, Counter):
        return None
    good = bad = 0.0
    found = False
    for labels, value in inst.samples():
        if not _match(labels, obj.labels):
            continue
        found = True
        try:
            status = int(labels.get("status", "0"))
        except ValueError:
            status = 0
        if status >= obj.bad_status_min:
            bad += value
        else:
            good += value
    return (good, bad) if found else None


def _read_gauge(
    metrics: MetricsRegistry, obj: SLOObjective
) -> Optional[float]:
    """Worst (max) non-negative matching sample of a gauge family, or
    None when absent / every sample carries the ``-1`` abstention
    sentinel — "no data is never a verdict"."""
    inst = metrics.instrument(obj.metric)
    if inst is None or not isinstance(inst, Gauge):
        return None
    values = [
        value
        for labels, value in inst.samples()
        if _match(labels, obj.labels) and value >= 0
    ]
    return max(values) if values else None


def _read_gauge_by_label(
    metrics: MetricsRegistry, obj: SLOObjective
) -> Optional[Dict[str, float]]:
    """Per-``per_label``-value worst (max) non-negative sample of a
    gauge family — one independent reading per label value (per
    partition, docs/storage.md#partitioning). None when the family is
    absent or every matching sample abstains."""
    inst = metrics.instrument(obj.metric)
    if inst is None or not isinstance(inst, Gauge):
        return None
    out: Dict[str, float] = {}
    for labels, value in inst.samples():
        if not _match(labels, obj.labels) or value < 0:
            continue
        key = labels.get(obj.per_label or "", "")
        if key in out:
            out[key] = max(out[key], value)
        else:
            out[key] = value
    return out or None


# -- the engine ---------------------------------------------------------------


class SLOEngine:
    """Evaluates a set of objectives against one process's registry.

    One lock guards the window state; ledger appends (fsync) happen
    OUTSIDE it — the module-wide never-block-under-a-lock discipline.
    Clocks are injected: ``clock`` orders the windows (monotonic),
    ``wall`` only stamps ledger lines for humans.
    """

    def __init__(
        self,
        metrics: MetricsRegistry,
        objectives: Sequence[SLOObjective],
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
        ledger_path: Optional[str] = None,
        node: str = "",
        flight=None,
    ):
        self.metrics = metrics
        self.objectives = tuple(objectives)
        self.clock = clock
        self.wall = wall
        #: None defers to the env at append time, like quality snapshots
        self.ledger_path = ledger_path
        self.node = node
        self.flight = flight
        self._lock = threading.Lock()
        # Entries are keyed by NAME: one per objective, except
        # ``per_label`` gauge objectives, which expand into one entry
        # per observed label value (``freshness[2]``) — each with its
        # own window series and fire/clear state machine. The flat
        # name starts as a visible abstaining placeholder and retires
        # when the first per-label reading arrives.
        self._series: Dict[str, _Series] = {
            obj.name: _Series() for obj in self.objectives
        }
        self._state: Dict[str, dict] = {
            obj.name: self._fresh_state() for obj in self.objectives
        }
        self._burn_gauge = metrics.gauge(
            "pio_slo_burn_rate",
            "Error-budget burn rate per objective and window "
            "(-1 = abstaining: source series absent or too thin)",
            labelnames=("objective", "window"),
        )
        self._state_gauge = metrics.gauge(
            "pio_slo_alert_state",
            "Alert state per objective (-1 abstaining, 0 ok, 1 firing)",
            labelnames=("objective",),
        )
        self._alerts = metrics.counter(
            "pio_slo_alerts_total",
            "Alert transitions by objective and event (fire / clear)",
            labelnames=("objective", "event"),
        )
        for obj in self.objectives:
            self._state_gauge.set(-1.0, objective=obj.name)
            for window in ("fast", "slow"):
                self._burn_gauge.set(
                    -1.0, objective=obj.name, window=window
                )

    @staticmethod
    def _fresh_state() -> dict:
        return {
            "state": _OK,
            "abstaining": True,
            "burn_fast": None,
            "burn_slow": None,
            "fired": 0,
            "cleared": 0,
        }

    def _ensure_entry(self, name: str) -> None:
        if name not in self._state:
            self._series[name] = _Series()
            self._state[name] = self._fresh_state()

    def _sub_entries(self, obj: SLOObjective) -> List[str]:
        prefix = obj.name + "["
        return sorted(n for n in self._state if n.startswith(prefix))

    # -- evaluation --------------------------------------------------------
    def _burns(
        self, obj: SLOObjective, series: _Series, now: float
    ) -> Tuple[Optional[float], Optional[float]]:
        if obj.kind == "ratio":
            burns = []
            budget = 1.0 - obj.target
            for window_s in (obj.fast_window_s, obj.slow_window_s):
                delta = series.ratio_window(now, window_s)
                if delta is None:
                    burns.append(None)
                    continue
                dgood, dbad = delta
                total = dgood + dbad
                if total < obj.min_window_events:
                    burns.append(None)  # too thin to judge — abstain
                    continue
                burns.append((dbad / total) / budget)
            return burns[0], burns[1]
        burns = []
        for window_s in (obj.fast_window_s, obj.slow_window_s):
            mean = series.gauge_window(now, window_s)
            burns.append(
                None if mean is None else mean / float(obj.max_value)
            )
        return burns[0], burns[1]

    def evaluate(self) -> dict:
        """One tick: sample every objective's source family, update the
        windows, run the fire/clear state machines, persist transitions.
        Returns the post-tick summary."""
        # refresh callback gauges (feed lag, PSI, breaker states ride
        # collect-time callbacks) before reading them
        self.metrics.collect()
        now = self.clock()
        transitions: List[dict] = []
        with self._lock:
            for obj in self.objectives:
                if obj.kind == "gauge" and obj.per_label:
                    self._evaluate_per_label(obj, now, transitions)
                    continue
                sample = None
                gauge_absent = False
                if obj.kind == "ratio":
                    observed = _read_ratio(self.metrics, obj)
                    if observed is not None:
                        sample = (now, observed[0], observed[1])
                else:
                    value = _read_gauge(self.metrics, obj)
                    if value is not None:
                        sample = (now, value)
                    else:
                        # the source went away (or is exporting the -1
                        # sentinel): stale window samples are not a
                        # verdict about NOW — abstain outright
                        gauge_absent = True
                self._evaluate_entry(
                    obj, obj.name, sample, gauge_absent, now, transitions
                )
        # durable + counter + flight work OUTSIDE the lock
        for record in transitions:
            event = "fire" if record["state"] == _FIRING else "clear"
            self._alerts.inc(1, objective=record["objective"], event=event)
            self._append(record)
            if self.flight is not None:
                try:
                    self.flight.record(
                        "alert", f"slo.{record['objective']}",
                        state=record["state"],
                        burnFast=record["burnFast"],
                        burnSlow=record["burnSlow"],
                    )
                except Exception:
                    pass  # forensics must never fail the evaluator
        return self.summary()

    def _evaluate_per_label(
        self, obj: SLOObjective, now: float, transitions: List[dict]
    ) -> None:
        """One independent entry per observed ``per_label`` value.
        Family absent: every known entry holds its state on abstention
        (a FIRING partition never clears on data loss); with no entry
        ever observed, the flat placeholder stays visibly abstaining.
        Caller holds the lock."""
        readings = _read_gauge_by_label(self.metrics, obj)
        known = self._sub_entries(obj)
        if not readings:
            for name in known or ():
                self._evaluate_entry(obj, name, None, True, now, transitions)
            if not known and obj.name in self._state:
                self._evaluate_entry(
                    obj, obj.name, None, True, now, transitions
                )
            return
        if not known and obj.name in self._state:
            # first real reading: the placeholder retires (its exported
            # gauge row stays -1 = abstaining, which is the truth)
            self._state.pop(obj.name)
            self._series.pop(obj.name, None)
        current = {f"{obj.name}[{key}]": key for key in readings}
        for name in sorted(current):
            self._ensure_entry(name)
            self._evaluate_entry(
                obj, name, (now, readings[current[name]]), False, now,
                transitions,
            )
        for name in known:
            if name not in current:
                # the label row vanished (node stopped exporting that
                # partition): data loss, not recovery — state holds
                self._evaluate_entry(obj, name, None, True, now, transitions)

    def _evaluate_entry(
        self,
        obj: SLOObjective,
        name: str,
        sample,
        gauge_absent: bool,
        now: float,
        transitions: List[dict],
    ) -> None:
        """Window update + fire/clear state machine for ONE entry
        (an objective, or one per-label sub-entry). Caller holds the
        lock."""
        series = self._series[name]
        state = self._state[name]
        if sample is not None:
            series.add(sample, obj.slow_window_s * 1.5)
        if gauge_absent:
            burn_fast = burn_slow = None
        else:
            burn_fast, burn_slow = self._burns(obj, series, now)
        abstaining = burn_fast is None or burn_slow is None
        state["burn_fast"] = burn_fast
        state["burn_slow"] = burn_slow
        state["abstaining"] = abstaining
        if not abstaining:
            if (
                state["state"] == _OK
                and burn_fast >= obj.burn_threshold
                and burn_slow >= obj.burn_threshold
            ):
                state["state"] = _FIRING
                state["fired"] += 1
                transitions.append(
                    self._transition(obj, _FIRING, state, name)
                )
            elif (
                state["state"] == _FIRING
                and burn_fast < obj.clear_threshold
            ):
                state["state"] = _OK
                state["cleared"] += 1
                transitions.append(
                    self._transition(obj, "CLEARED", state, name)
                )
        # export: -1 abstaining / 0 ok / 1 firing; a FIRING
        # objective that loses its data keeps exporting 1 — an
        # alert never clears on data loss
        if state["state"] == _FIRING:
            self._state_gauge.set(1.0, objective=name)
        elif abstaining:
            self._state_gauge.set(-1.0, objective=name)
        else:
            self._state_gauge.set(0.0, objective=name)
        for window, burn in (
            ("fast", burn_fast), ("slow", burn_slow)
        ):
            self._burn_gauge.set(
                -1.0 if burn is None else burn,
                objective=name, window=window,
            )

    def _transition(
        self,
        obj: SLOObjective,
        state: str,
        snapshot: dict,
        name: Optional[str] = None,
    ) -> dict:
        return {
            "schema": ALERT_SCHEMA,
            "kind": "alert",
            "objective": name or obj.name,
            "metric": obj.metric,
            "state": state,
            "burnFast": _round(snapshot["burn_fast"]),
            "burnSlow": _round(snapshot["burn_slow"]),
            "burnThreshold": obj.burn_threshold,
            "node": self.node,
            "at": self.wall(),
        }

    def _append(self, record: dict) -> None:
        path = self.ledger_path or os.environ.get(ALERT_LEDGER_ENV)
        if not path:
            return
        try:
            from .perfledger import append_record

            append_record(path, record)
        except OSError:
            pass  # a read-only ledger degrades to in-memory alerting

    # -- reporting ---------------------------------------------------------
    def summary(self) -> dict:
        with self._lock:
            objectives = []
            for obj in self.objectives:
                names = (
                    [obj.name] if obj.name in self._state else []
                ) + self._sub_entries(obj)
                for name in names:
                    entry = self._state[name]
                    objectives.append(
                        {
                            "name": name,
                            "kind": obj.kind,
                            "metric": obj.metric,
                            "state": entry["state"],
                            "abstaining": entry["abstaining"],
                            "burnFast": _round(entry["burn_fast"]),
                            "burnSlow": _round(entry["burn_slow"]),
                            "burnThreshold": obj.burn_threshold,
                            "fired": entry["fired"],
                            "cleared": entry["cleared"],
                        }
                    )
        return {
            "objectives": objectives,
            "firing": sum(
                1 for o in objectives if o["state"] == _FIRING
            ),
        }

    def firing(self) -> List[str]:
        with self._lock:
            return [
                name
                for name, state in self._state.items()
                if state["state"] == _FIRING
            ]


def _round(value: Optional[float]) -> Optional[float]:
    return None if value is None else round(float(value), 4)


# -- per-server health plane --------------------------------------------------


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Knobs of one server's health plane (``ServerConfig.health``)."""

    #: alert-ledger JSONL path; None reads PIO_ALERT_LEDGER at append
    alert_ledger: Optional[str] = None
    #: flight-recorder dump dir; None reads PIO_FLIGHT_DIR
    flight_dir: Optional[str] = None
    #: background evaluation cadence; None reads PIO_SLO_TICK_S
    #: (default 15 s); 0 disables the thread (explicit tick() only)
    tick_s: Optional[float] = None
    #: objective override; None = default_objectives(kind)
    objectives: Optional[Tuple[SLOObjective, ...]] = None


class HealthPlane:
    """One server's health stack: SLO engine + stall watchdog + a
    reference to the process flight recorder, evaluated together on one
    background ticker (``GET /health.json`` reads it, ``pio health``
    scrapes it fleet-wide)."""

    def __init__(
        self,
        metrics: MetricsRegistry,
        kind: str,
        clock: Callable[[], float] = time.monotonic,
        config: Optional[HealthConfig] = None,
        flight=None,
        node: str = "",
    ):
        from .flight import StallWatchdog, arm, default_recorder

        self.kind = kind
        self.config = config or HealthConfig()
        self.flight = flight if flight is not None else default_recorder()
        # arm the atexit/faulthandler crash dump — a process-level
        # decision, so env-driven only (PIO_FLIGHT_DIR; no-op unset,
        # idempotent, never signal handlers from library code)
        arm()
        objectives = (
            self.config.objectives
            if self.config.objectives is not None
            else default_objectives(kind)
        )
        self.engine = SLOEngine(
            metrics,
            objectives,
            clock=clock,
            ledger_path=self.config.alert_ledger,
            node=node or kind,
            flight=self.flight,
        )
        self.watchdog = StallWatchdog(
            metrics,
            clock=clock,
            flight=self.flight,
            dump_dir=self.config.flight_dir,
        )
        if self.config.tick_s is not None:
            self._tick_s = float(self.config.tick_s)
        else:
            self._tick_s = float(
                os.environ.get(TICK_ENV, str(DEFAULT_TICK_S))
            )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def tick(self) -> dict:
        """One evaluation round (the background loop's body; drills and
        tests call it directly on injected clocks)."""
        self.watchdog.check()
        return self.engine.evaluate()

    def start(self) -> None:
        if self._tick_s <= 0 or self._thread is not None:
            return

        def loop() -> None:
            while not self._stop.wait(self._tick_s):
                try:
                    self.tick()
                except Exception:
                    pass  # the watcher must never take the server down

        self._thread = threading.Thread(
            target=loop, name=f"health-{self.kind}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=2.0)

    def health_json(self) -> dict:
        out = self.engine.summary()
        out["kind"] = self.kind
        out["stalls"] = self.watchdog.summary()
        return out
