"""Performance observability: jit compile/retrace telemetry + phase profiling.

The ROADMAP's verdict on rounds 1–5 is that the control plane matured
while BENCH stayed flat — and nothing in the system could *say why*:
time lost to XLA compiles, silent per-request retraces, or gather-bound
solves all looked identical from outside. This module is the seeing
layer (docs/observability.md#profiling):

- :class:`JitTelemetry` — process-wide compile/retrace accounting at the
  jit boundary. Call sites (trainer solves in ``ops/als.py``, the
  serving top-k dispatch in ``ops/scoring.py``, continuous fold-in in
  ``continuous/foldin.py``) route jitted calls through
  :meth:`JitTelemetry.call` / :meth:`JitTelemetry.wrap`; a call that
  grows the jitted function's compilation cache is a compile, and any
  compile after a function's first is a **retrace** (a new signature —
  the silent 20-40 s tax ``ops/scoring.pad_pow2`` exists to bound).
  Bound registries expose ``pio_jit_compiles_total{fn}`` /
  ``pio_jit_retraces_total{fn}`` / ``pio_jit_compile_seconds{fn}`` on
  ``/metrics``; a live request's ambient trace context gets a
  ``jit.compile`` span so an unexpected compile is visible in
  ``pio trace`` timelines. ``attach_monitoring()`` additionally taps
  ``jax.monitoring`` for backend-compile durations and persistent
  compilation-cache hit/miss counts (wired in by
  ``utils/jax_cache.enable_compilation_cache``).
- :class:`PhaseProfiler` — ``utils/profiling.StepTimer`` grown device
  fences and roofline accounting: each phase records wall time, a
  fenced (``block_until_ready``) device-complete time, and optional
  FLOP/byte estimates from which MFU and HBM-bandwidth utilization are
  computed against the v5e reference peaks (the ``bench.py`` numbers,
  now shared). Disabled (``PIO_PROFILE`` unset), a phase is a no-op
  context that never touches the clock or the device — hooks may stay
  in production paths.

Like the rest of ``obs/``, importing this module requires neither jax
nor numpy; everything device-facing is imported lazily inside the few
functions that need it.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional

from .metrics import MetricsRegistry
from .trace import current_context

__all__ = [
    "DEVICE_PEAKS",
    "JitTelemetry",
    "PhaseProfiler",
    "PROFILE_ENV",
    "default_telemetry",
    "profiling_enabled",
    "render_profile_report",
    "roofline",
]

#: Environment switch for the *deep* profiling hooks (device fences,
#: per-phase accounting). The cheap jit compile/retrace counters are
#: always on — an int compare per dispatch.
PROFILE_ENV = "PIO_PROFILE"

#: Reference device peaks for roofline estimates. v5e: 197 TFLOP/s bf16
#: MXU → ~half attainable for f32 solves; 819 GB/s HBM. The same
#: constants bench.py has used since round 2 — one home now.
DEVICE_PEAKS: Dict[str, Dict[str, float]] = {
    "tpu-v5e": {"flops_per_s_f32": 98.5e12, "hbm_bytes_per_s": 819e9},
}

#: The peaks roofline estimates are computed against when the caller
#: does not name a device (estimates are then explicitly labelled as
#: v5e-referenced, the convention bench.py set).
REFERENCE_DEVICE = "tpu-v5e"

#: compile-duration samples kept per function for replay-on-bind and
#: reports; compiles are rare, so a small cap loses nothing real
_MAX_SAMPLES = 256


def profiling_enabled(env: Optional[Dict[str, str]] = None) -> bool:
    """Is deep profiling (``PIO_PROFILE``) switched on?"""
    value = (env if env is not None else os.environ).get(PROFILE_ENV, "")
    return value not in ("", "0", "off", "false")


def roofline(
    flops: float,
    hbm_bytes: float,
    seconds: float,
    peaks: Optional[Dict[str, float]] = None,
) -> Dict[str, float]:
    """FLOP/byte/time → achieved TFLOP/s, MFU and HBM-bandwidth
    utilization against ``peaks`` (default: the v5e reference — callers
    on other devices label the result accordingly, as bench.py does)."""
    peaks = peaks if peaks is not None else DEVICE_PEAKS[REFERENCE_DEVICE]
    if seconds <= 0.0:
        return {"tflops_per_s": 0.0, "mfu": 0.0, "hbm_util": 0.0}
    mfu = flops / seconds / peaks["flops_per_s_f32"]
    hbm = hbm_bytes / seconds / peaks["hbm_bytes_per_s"]
    return {
        "tflops_per_s": flops / seconds / 1e12,
        "mfu": mfu,
        "hbm_util": hbm,
    }


class _InstrumentedJit:
    """Callable wrapper around one jitted function: every call routes
    through the telemetry's compile accounting; every other attribute
    (``.lower``, ``._cache_size``, …) forwards to the wrapped function
    so AOT tooling keeps working against the instrumented name."""

    __slots__ = ("_telemetry", "_name", "__wrapped__")

    def __init__(self, telemetry: "JitTelemetry", name: str, fn):
        self._telemetry = telemetry
        self._name = name
        self.__wrapped__ = fn

    def __call__(self, *args, **kwargs):
        return self._telemetry.call(
            self._name, self.__wrapped__, *args, **kwargs
        )

    def __getattr__(self, item):
        return getattr(self.__wrapped__, item)


class JitTelemetry:
    """Process-wide compile/retrace accounting at the jit boundary.

    Internal state is the source of truth (training and bench read it
    without any server); bound :class:`MetricsRegistry` instances mirror
    it onto ``/metrics``. Binding replays current totals into the fresh
    registry's counters so a server created *after* its deploy-time
    compiles still exposes them. Registries are held weakly — a test
    suite creating hundreds of servers must not grow a permanent list.
    """

    def __init__(self, clock: Callable[[], float] = time.perf_counter):
        self._clock = clock
        self._lock = threading.Lock()
        #: fn name -> {"compiles", "retraces", "samples": [seconds, ...]}
        self._fns: Dict[str, dict] = {}
        #: fn -> highest cache size already credited. Two threads racing
        #: the same first compile both see the cache grow (the loser
        #: waits on jax's compile lock, then reads after > before);
        #: crediting only growth BEYOND the recorded high-water mark
        #: keeps the count at one compile, no phantom retrace. Keyed by
        #: the fn itself, weakly: a GC'd jitted fn (lru_cache eviction)
        #: drops its mark instead of leaking it onto an id()-recycled
        #: successor, and the map cannot grow past the live fn set.
        self._seen_sizes: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary()
        )
        self._cache_hits = 0
        self._cache_misses = 0
        self._backend_compiles = 0
        self._backend_samples: List[float] = []
        self._bound: List[weakref.ref] = []
        self._monitoring = False

    # -- the jit boundary --------------------------------------------------
    def call(self, name: str, fn, *args, **kwargs):
        """Call ``fn`` (a jitted callable), detecting whether THIS call
        compiled by probing its compilation-cache size around the call.
        A non-jitted callable (no ``_cache_size``) passes through
        untouched — callers never need to know which they hold."""
        size_fn = getattr(fn, "_cache_size", None)
        if size_fn is None:
            return fn(*args, **kwargs)
        try:
            before = size_fn()
        except Exception:
            return fn(*args, **kwargs)
        t0 = self._clock()
        out = fn(*args, **kwargs)
        try:
            after = size_fn()
        except Exception:
            after = before
        if after > before:
            with self._lock:
                try:
                    credited = self._seen_sizes.get(fn, 0)
                    fresh = after > max(before, credited)
                    if fresh:
                        self._seen_sizes[fn] = after
                except TypeError:
                    # unhashable/non-weakrefable callable: fall back to
                    # the raw probe (worst case: a racing first compile
                    # double-counts on such a fn)
                    fresh = True
            if fresh:
                self._record_compile(name, self._clock() - t0)
        return out

    def wrap(self, name: str, fn) -> _InstrumentedJit:
        """Permanently instrument a module-level jitted function."""
        return _InstrumentedJit(self, name, fn)

    def _record_compile(self, name: str, seconds: float) -> None:
        with self._lock:
            st = self._fns.setdefault(
                name, {"compiles": 0, "retraces": 0, "samples": []}
            )
            retrace = st["compiles"] >= 1
            st["compiles"] += 1
            if retrace:
                st["retraces"] += 1
            if len(st["samples"]) < _MAX_SAMPLES:
                st["samples"].append(float(seconds))
            bound = self._live_registries()
        for registry in bound:
            inst = self._instruments(registry)
            inst["compiles"].inc(1, fn=name)
            if retrace:
                inst["retraces"].inc(1, fn=name)
            inst["compile_s"].observe(seconds, fn=name)
        # a compile inside a live request is exactly the thing a trace
        # should show: record it against the ambient span, if any
        ctx = current_context()
        if ctx is not None:
            try:
                tracer = ctx.tracer
                tracer.record(
                    "jit.compile",
                    tracer.child_context(ctx),
                    ctx.span_id,
                    start_wall=tracer.wall() - seconds,
                    duration_s=seconds,
                    tags={"fn": name, "retrace": retrace},
                )
            except Exception:
                pass  # telemetry must never fail the traced call

    # -- jax.monitoring taps ----------------------------------------------
    def attach_monitoring(self) -> bool:
        """Tap ``jax.monitoring`` for backend-compile durations and
        persistent compilation-cache hit/miss events. Idempotent,
        best-effort (False when jax is unavailable); listeners are
        process-global and registered at most once."""
        with self._lock:
            if self._monitoring:
                return True
            self._monitoring = True
        try:
            import jax.monitoring as monitoring
        except Exception:
            with self._lock:
                self._monitoring = False
            return False

        def on_event(name: str, **kwargs) -> None:
            if name.endswith("/cache_hits"):
                with self._lock:
                    self._cache_hits += 1
            elif name.endswith("/cache_misses"):
                with self._lock:
                    self._cache_misses += 1

        def on_duration(name: str, duration: float, **kwargs) -> None:
            if not name.endswith("backend_compile_duration"):
                return
            with self._lock:
                self._backend_compiles += 1
                if len(self._backend_samples) < _MAX_SAMPLES:
                    self._backend_samples.append(float(duration))
                bound = self._live_registries()
            for registry in bound:
                self._instruments(registry)["backend_s"].observe(duration)

        try:
            monitoring.register_event_listener(on_event)
            monitoring.register_event_duration_secs_listener(on_duration)
        except Exception:
            # un-latch so a later call may retry; a half-registered pair
            # (first succeeded, second raised) at worst re-registers the
            # event listener, double-counting being the lesser evil than
            # a silently-dead tap for the process lifetime
            with self._lock:
                self._monitoring = False
            return False
        return True

    # -- registry mirroring ------------------------------------------------
    def _instruments(self, registry: MetricsRegistry) -> dict:
        """Idempotent instrument lookup on a bound registry (get-or-create
        is the registry's own contract)."""
        return {
            "compiles": registry.counter(
                "pio_jit_compiles_total",
                "XLA compiles observed at instrumented jit boundaries",
                labelnames=("fn",),
            ),
            "retraces": registry.counter(
                "pio_jit_retraces_total",
                "Compiles after a function's first — new-signature "
                "retraces",
                labelnames=("fn",),
            ),
            "compile_s": registry.histogram(
                "pio_jit_compile_seconds",
                "Wall time of jitted calls that triggered a compile",
                labelnames=("fn",),
            ),
            "backend_s": registry.histogram(
                "pio_jit_backend_compile_seconds",
                "XLA backend compile durations (jax.monitoring, whole "
                "process)",
            ),
        }

    def _live_registries(self) -> List[MetricsRegistry]:
        """Caller holds ``_lock``. Prunes dead weakrefs in passing."""
        live, refs = [], []
        for ref in self._bound:
            registry = ref()
            if registry is not None:
                live.append(registry)
                refs.append(ref)
        self._bound = refs
        return live

    def bind(self, registry: MetricsRegistry) -> None:
        """Mirror this telemetry onto ``registry`` (``/metrics``): create
        the instrument families, replay current totals (compiles that
        happened before the server existed — e.g. deploy-time serving
        warmup — must not vanish from exposition), and register the
        cache hit/miss gauges. Idempotent per registry."""
        with self._lock:
            if any(ref() is registry for ref in self._bound):
                return
            self._bound.append(weakref.ref(registry))
            fns = {
                name: (st["compiles"], st["retraces"], list(st["samples"]))
                for name, st in self._fns.items()
            }
            backend = list(self._backend_samples)
        inst = self._instruments(registry)
        for name, (compiles, retraces, samples) in fns.items():
            if compiles:
                inst["compiles"].inc(compiles, fn=name)
            if retraces:
                inst["retraces"].inc(retraces, fn=name)
            for seconds in samples:
                inst["compile_s"].observe(seconds, fn=name)
        for seconds in backend:
            inst["backend_s"].observe(seconds)
        registry.gauge_callback(
            "pio_jit_cache_hits",
            self._hits_locked,
            "Persistent compilation-cache hits (jax.monitoring)",
        )
        registry.gauge_callback(
            "pio_jit_cache_misses",
            self._misses_locked,
            "Persistent compilation-cache misses (jax.monitoring)",
        )

    def _hits_locked(self) -> int:
        with self._lock:
            return self._cache_hits

    def _misses_locked(self) -> int:
        with self._lock:
            return self._cache_misses

    # -- snapshots ---------------------------------------------------------
    def snapshot(self) -> dict:
        """Current totals, JSON-safe: ``{"fns": {name: {compiles,
        retraces, compile_s}}, "cache": {hits, misses, backend_compiles,
        backend_compile_s}}``."""
        with self._lock:
            return {
                "fns": {
                    name: {
                        "compiles": st["compiles"],
                        "retraces": st["retraces"],
                        "compile_s": round(sum(st["samples"]), 4),
                    }
                    for name, st in self._fns.items()
                },
                "cache": {
                    "hits": self._cache_hits,
                    "misses": self._cache_misses,
                    "backend_compiles": self._backend_compiles,
                    "backend_compile_s": round(
                        sum(self._backend_samples), 4
                    ),
                },
            }

    def delta_since(self, before: dict) -> dict:
        """``snapshot() - before``: what happened during one run (the
        shape persisted into ``PIO_TRAIN_PROFILE``). Functions with a
        zero delta are dropped."""
        now = self.snapshot()
        fns = {}
        for name, st in now["fns"].items():
            prev = before.get("fns", {}).get(name, {})
            compiles = st["compiles"] - prev.get("compiles", 0)
            retraces = st["retraces"] - prev.get("retraces", 0)
            if compiles <= 0 and retraces <= 0:
                continue
            fns[name] = {
                "compiles": compiles,
                "retraces": retraces,
                "compile_s": round(
                    st["compile_s"] - prev.get("compile_s", 0.0), 4
                ),
            }
        prev_cache = before.get("cache", {})
        cache = {
            key: (
                round(now["cache"][key] - prev_cache.get(key, 0), 4)
                if isinstance(now["cache"][key], float)
                else now["cache"][key] - prev_cache.get(key, 0)
            )
            for key in now["cache"]
        }
        return {"fns": fns, "cache": cache}


_SINGLETON_LOCK = threading.Lock()
_default: Optional[JitTelemetry] = None


def default_telemetry() -> JitTelemetry:
    """The process-wide telemetry instance every instrumented boundary
    reports into (jit caches are process state, so is their telemetry)."""
    global _default
    with _SINGLETON_LOCK:
        if _default is None:
            _default = JitTelemetry()
        return _default


# -- phase profiling --------------------------------------------------------


class _NullPhase:
    """The disabled-path phase handle AND context manager: every method
    is a no-op so a production code path pays an attribute call and
    nothing else when ``PIO_PROFILE`` is off."""

    __slots__ = ()

    def __enter__(self) -> "_NullPhase":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def fence(self, value=None):
        return value


_NULL_PHASE = _NullPhase()


class _Phase:
    """One enabled phase: wall time always; ``fence(value)`` blocks until
    ``value``'s device work completes and records the device-complete
    time (without a fence, device_s == wall_s — an *unfenced dispatch*
    measurement, which the report labels as such is not: callers that
    care fence)."""

    __slots__ = ("_profiler", "_t0", "device_s")

    def __init__(self, profiler: "PhaseProfiler", t0: float):
        self._profiler = profiler
        self._t0 = t0
        self.device_s: Optional[float] = None

    def fence(self, value=None):
        self._profiler._fence(value)
        self.device_s = self._profiler._clock() - self._t0
        return value


class _PhaseCtx:
    __slots__ = ("_profiler", "_name", "_flops", "_bytes", "_phase")

    def __init__(self, profiler, name, flops, hbm_bytes):
        self._profiler = profiler
        self._name = name
        self._flops = flops
        self._bytes = hbm_bytes
        self._phase: Optional[_Phase] = None

    def __enter__(self) -> _Phase:
        self._phase = _Phase(self._profiler, self._profiler._clock())
        return self._phase

    def __exit__(self, *exc) -> None:
        ph = self._phase
        wall = self._profiler._clock() - ph._t0
        self._profiler._record(
            self._name,
            wall_s=wall,
            device_s=ph.device_s if ph.device_s is not None else wall,
            flops=self._flops,
            hbm_bytes=self._bytes,
        )


def _default_fence(value) -> None:
    try:
        import jax

        jax.block_until_ready(value)
    except Exception:
        pass  # device-free host (or host values): nothing to fence


class PhaseProfiler:
    """``StepTimer`` extended with device fencing and roofline
    accounting (docs/observability.md#profiling).

    ::

        prof = PhaseProfiler(enabled=True)
        with prof.phase("solve", flops=F, hbm_bytes=B) as ph:
            out = jitted(x)
            ph.fence(out)          # device-complete, not dispatch, time
        prof.summary()["solve"]["mfu"]  # vs the v5e reference peaks

    ``enabled=None`` reads ``PIO_PROFILE``; disabled, :meth:`phase`
    returns a shared no-op context that never calls the clock or the
    fence — the near-zero-cost contract ``tests/test_perf.py`` pins.
    ``clock`` and ``fence`` are injectable for sleep-free, device-free
    tests.
    """

    def __init__(
        self,
        enabled: Optional[bool] = None,
        clock: Callable[[], float] = time.perf_counter,
        fence: Optional[Callable] = None,
        peaks: Optional[Dict[str, float]] = None,
    ):
        self.enabled = profiling_enabled() if enabled is None else enabled
        self._clock = clock
        self._fence = fence if fence is not None else _default_fence
        self._peaks = peaks
        self._lock = threading.Lock()
        self._phases: Dict[str, dict] = {}

    def phase(self, name: str, flops: float = 0.0, hbm_bytes: float = 0.0):
        if not self.enabled:
            return _NULL_PHASE
        return _PhaseCtx(self, name, float(flops), float(hbm_bytes))

    def _record(self, name, wall_s, device_s, flops, hbm_bytes) -> None:
        with self._lock:
            st = self._phases.setdefault(
                name,
                {
                    "count": 0,
                    "wall_s": 0.0,
                    "device_s": 0.0,
                    "flops": 0.0,
                    "hbm_bytes": 0.0,
                },
            )
            st["count"] += 1
            st["wall_s"] += wall_s
            st["device_s"] += device_s
            st["flops"] += flops
            st["hbm_bytes"] += hbm_bytes

    def record(
        self,
        name: str,
        wall_s: float,
        device_s: Optional[float] = None,
        flops: float = 0.0,
        hbm_bytes: float = 0.0,
    ) -> None:
        """Adopt an externally measured phase (e.g. ``ops/als.py``'s
        fenced per-iteration timings) into the same summary."""
        if not self.enabled:
            return
        self._record(
            name,
            wall_s=wall_s,
            device_s=device_s if device_s is not None else wall_s,
            flops=flops,
            hbm_bytes=hbm_bytes,
        )

    def summary(self) -> Dict[str, dict]:
        """Per-phase totals + roofline estimates (vs the v5e reference
        peaks unless the profiler was built with explicit ``peaks``) —
        JSON-safe, the ``pio profile`` report's data."""
        with self._lock:
            phases = {
                name: dict(st) for name, st in self._phases.items()
            }
        for st in phases.values():
            st.update(
                {
                    key: round(value, 6)
                    for key, value in roofline(
                        st["flops"],
                        st["hbm_bytes"],
                        st["device_s"],
                        self._peaks,
                    ).items()
                }
            )
            st["wall_s"] = round(st["wall_s"], 6)
            st["device_s"] = round(st["device_s"], 6)
        return phases


# -- report rendering (pio profile) -----------------------------------------


def render_profile_report(
    title: str,
    phases: Optional[Dict[str, dict]] = None,
    jit: Optional[Dict[str, dict]] = None,
    cache: Optional[dict] = None,
    device: Optional[str] = None,
) -> str:
    """One-screen text report shared by every ``pio profile`` mode
    (smoke train, live-server scrape, completed instance). Inputs are
    plain dicts — the summary shapes of :class:`PhaseProfiler`,
    :meth:`JitTelemetry.snapshot` and the exposition scrape all fit."""
    lines = [f"pio profile — {title}" + (f" (device {device})" if device else "")]
    if phases:
        lines.append("")
        lines.append(
            f"{'phase':<24}{'count':>6}{'wall_s':>10}{'device_s':>10}"
            f"{'tflops/s':>10}{'mfu(v5e)':>10}{'hbm_util':>10}"
        )
        for name in sorted(phases):
            st = phases[name]
            lines.append(
                f"{name:<24}{st.get('count', 1):>6}"
                f"{st.get('wall_s', 0.0):>10.3f}"
                f"{st.get('device_s', st.get('wall_s', 0.0)):>10.3f}"
                f"{st.get('tflops_per_s', 0.0):>10.3f}"
                f"{st.get('mfu', 0.0):>10.4f}"
                f"{st.get('hbm_util', 0.0):>10.4f}"
            )
        lines.append(
            "  (mfu/hbm_util are roofline estimates vs the v5e reference "
            "peaks; on other devices read them as relative, like bench.py)"
        )
    if jit:
        lines.append("")
        lines.append(
            f"{'jit fn':<24}{'compiles':>9}{'retraces':>9}"
            f"{'compile_s':>11}"
        )
        for name in sorted(jit):
            st = jit[name]
            lines.append(
                f"{name:<24}{st.get('compiles', 0):>9.0f}"
                f"{st.get('retraces', 0):>9.0f}"
                f"{st.get('compile_s', 0.0):>11.3f}"
            )
    if cache is not None:
        lines.append("")
        lines.append(
            "compilation cache: "
            f"hits={cache.get('hits', 0):.0f} "
            f"misses={cache.get('misses', 0):.0f} "
            f"backend_compiles={cache.get('backend_compiles', 0):.0f} "
            f"backend_compile_s={cache.get('backend_compile_s', 0.0):.3f}"
        )
    if not phases and not jit and cache is None:
        lines.append("(no profile data)")
    return "\n".join(lines)
