"""Lint engine: file walking, AST context, suppressions, reporters.

Everything here is stdlib-only (``ast`` + ``re``) — the linter must run
in environments where jax itself cannot import (pre-commit hooks, CI
images without an accelerator stack), so it never imports the modules it
analyzes.

The engine's job is mechanics; the rules live in :mod:`rules_mosaic`
and :mod:`rules_jit`. A rule is a :class:`Rule` subclass whose
``check(ctx)`` yields :class:`Finding` objects against one
:class:`FileContext`. The engine then applies suppression comments
(``# pio: lint-ok[rule-id] reason``) and renders text or JSON.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set

from ..utils.durability import atomic_write_bytes

#: bumped whenever any rule's judgment OR the fact-extraction schema
#: changes: the incremental cache keys every stored result (findings
#: AND the per-file fact tables flow rules judge) on (this, the
#: registered rule set), so an analysis edit invalidates the whole
#: cache instead of serving verdicts a previous version produced. A
#: stale cache can therefore never suppress a finding the current
#: rules would raise.
RULES_VERSION = "3"

#: ``# pio: lint-ok[rule-a, rule-b] free-text reason``
_SUPPRESS_RE = re.compile(
    r"#\s*pio:\s*lint-ok\[([A-Za-z0-9_\-, ]+)\]\s*(.*?)\s*$"
)

#: Attribute accesses on a traced value that are static at trace time —
#: branching on these inside ``@jit`` is fine.
STATIC_VALUE_ATTRS = frozenset(
    {"shape", "ndim", "dtype", "size", "aval", "sharding"}
)

#: threading primitive constructors → the lock "kind" the concurrency
#: rules reason about. Semaphores and Events are hand-off primitives —
#: acquired on one thread, released on another by design — so the
#: with/finally discipline rules exempt them.
LOCK_KINDS = {
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
    "Semaphore": "semaphore",
    "BoundedSemaphore": "semaphore",
    "Event": "event",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding, pre- or post-suppression."""

    rule_id: str
    path: str
    line: int
    col: int
    message: str
    severity: str = "error"
    suppressed: bool = False
    suppress_reason: str = ""

    def as_dict(self) -> dict:
        return {
            "rule": self.rule_id,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "severity": self.severity,
            "message": self.message,
            "suppressed": self.suppressed,
            **(
                {"suppressReason": self.suppress_reason}
                if self.suppressed
                else {}
            ),
        }


class Rule:
    """Base class: subclasses set the class attributes and implement
    ``check``. ``id`` doubles as the suppression token."""

    id: str = ""
    severity: str = "error"
    #: one-line "what it catches" (the ``--list-rules`` output)
    short: str = ""
    #: the round-5 incident (or rationale) that motivated the rule
    motivation: str = ""

    def check(self, ctx: "FileContext") -> Iterator[Finding]:
        raise NotImplementedError

    def finding(
        self, ctx: "FileContext", node: ast.AST, message: str
    ) -> Finding:
        return Finding(
            rule_id=self.id,
            path=ctx.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
            severity=self.severity,
        )


@dataclasses.dataclass
class ClassScope:
    """Per-class lock/attribute facts the concurrency rules (family E)
    reason over: which ``self.*`` attributes are threading primitives,
    which methods exist, and which attributes some method writes while
    lexically inside a ``with self.<lock>:`` block."""

    node: ast.ClassDef
    name: str
    #: ``self.X = threading.Lock()`` style assignments anywhere in the
    #: class: attr name → kind ("lock" | "rlock" | "condition" |
    #: "semaphore" | "event")
    lock_attrs: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: direct methods by name
    methods: Dict[str, ast.FunctionDef] = dataclasses.field(
        default_factory=dict
    )
    #: attrs assigned/augassigned under ``with self.<lock>`` (lock,
    #: rlock or condition kind) in at least one method — the class's
    #: lock-guarded state, as inferred from its own locking discipline
    guarded_writes: Set[str] = dataclasses.field(default_factory=set)
    #: True when the class subclasses ``threading.Thread`` (its ``run``
    #: method executes on the spawned thread)
    is_thread_subclass: bool = False

    def mutex_attrs(self) -> Set[str]:
        """Lock attrs that provide mutual exclusion (not hand-off
        primitives)."""
        return {
            name
            for name, kind in self.lock_attrs.items()
            if kind in ("lock", "rlock", "condition")
        }


@dataclasses.dataclass
class _Suppression:
    line: int
    rule_ids: Set[str]
    reason: str
    #: True when the comment is the whole line — only these may cover the
    #: line below (a trailing suppression covers its own line only, so it
    #: can never silently absorb a second violation on the next line)
    comment_only: bool = True
    used: bool = False


@dataclasses.dataclass
class FileContext:
    """Everything a rule needs about one parsed file."""

    path: str
    source: str
    tree: ast.Module
    #: posix-style path used for scoping rules to known hot modules
    posix_path: str
    #: module-level integer constants (``_SPD_BLK = 128``) — lets the
    #: tiling rules resolve named block sizes
    int_constants: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: module-level string-tuple constants (``_HALF_STATICS = ("a",)``)
    str_tuple_constants: Dict[str, Sequence[str]] = dataclasses.field(
        default_factory=dict
    )
    #: FunctionDefs identified as Pallas kernels (passed to
    #: ``pl.pallas_call`` directly or via ``functools.partial``, plus
    #: module functions they call)
    kernels: List[ast.FunctionDef] = dataclasses.field(default_factory=list)
    has_pallas_call: bool = False
    #: per-kernel-name parameter names bound to SMEM blocks (read off the
    #: ``pallas_call`` in_specs literal) — scalar memory has no lane
    #: tiling, so the lane-alignment rules exempt these refs
    smem_params: Dict[str, Set[str]] = dataclasses.field(default_factory=dict)
    suppressions: List[_Suppression] = dataclasses.field(default_factory=list)
    #: class-scope lock/attribute facts (family E inputs)
    classes: List[ClassScope] = dataclasses.field(default_factory=list)
    #: module-level names bound to a threading primitive
    #: (``_LOCK = threading.Lock()``): name → kind
    module_locks: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: module-level names bound to a mutable container literal/ctor
    #: (``_REGISTRY = {}``): name → container kind ("dict"/"list"/"set")
    module_mutables: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: module-level names bound to ``contextvars.ContextVar(...)``
    module_contextvars: Set[str] = dataclasses.field(default_factory=set)

    def kernel_smem_params(self, kernel: ast.FunctionDef) -> Set[str]:
        return self.smem_params.get(kernel.name, set())

    # -- shared static-evaluation helpers used by the rule modules ------

    def const_int(self, node: ast.AST) -> Optional[int]:
        """Resolve ``node`` to an int: literal, unary minus, module-level
        constant name, or a foldable ``a op b`` of those."""
        if isinstance(node, ast.Constant) and isinstance(node.value, int) \
                and not isinstance(node.value, bool):
            return node.value
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
            inner = self.const_int(node.operand)
            return None if inner is None else -inner
        if isinstance(node, ast.Name):
            return self.int_constants.get(node.id)
        if isinstance(node, ast.BinOp):
            left = self.const_int(node.left)
            right = self.const_int(node.right)
            if left is None or right is None:
                return None
            if isinstance(node.op, ast.Mult):
                return left * right
            if isinstance(node.op, ast.Add):
                return left + right
            if isinstance(node.op, ast.Sub):
                return left - right
            if isinstance(node.op, ast.FloorDiv) and right != 0:
                return left // right
        return None

    def provably_multiple(self, node: ast.AST, m: int) -> bool:
        """True when ``node`` is statically provably a multiple of ``m``:
        a resolvable int with value % m == 0, a product with a provably-
        multiple factor, a sum/difference of provable multiples, or a
        ``_round_up(x, c)`` call with c % m == 0 (the repo's alignment
        idiom)."""
        value = self.const_int(node)
        if value is not None:
            return value % m == 0
        if isinstance(node, ast.BinOp):
            if isinstance(node.op, ast.Mult):
                return self.provably_multiple(
                    node.left, m
                ) or self.provably_multiple(node.right, m)
            if isinstance(node.op, (ast.Add, ast.Sub)):
                return self.provably_multiple(
                    node.left, m
                ) and self.provably_multiple(node.right, m)
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else ""
            )
            if name in ("_round_up", "round_up") and len(node.args) == 2:
                c = self.const_int(node.args[1])
                return c is not None and c % m == 0
        return False


# ---------------------------------------------------------------------------
# AST helpers shared by the rule modules
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> str:
    """``jax.lax.fori_loop`` → "jax.lax.fori_loop"; "" when not a plain
    name chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def call_name(node: ast.Call) -> str:
    """Trailing name of the called function: ``pl.pallas_call(...)`` →
    "pallas_call"."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def is_partial_call(node: ast.Call) -> bool:
    return call_name(node) in ("partial",)


def index_elements(sub: ast.Subscript) -> List[ast.AST]:
    """The subscript's index as a flat element list (``x[a, b]`` → [a, b];
    ``x[a]`` → [a])."""
    idx = sub.slice
    if isinstance(idx, ast.Tuple):
        return list(idx.elts)
    return [idx]


def subscript_base_name(sub: ast.Subscript) -> str:
    """Name the subscript is rooted at, looking through ``.at``:
    ``y_ref.at[...]`` → "y_ref", ``w2_ref[...]`` → "w2_ref"."""
    base = sub.value
    if isinstance(base, ast.Attribute) and base.attr == "at":
        base = base.value
    if isinstance(base, ast.Name):
        return base.id
    return ""


def is_none_constant(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value is None


# ---------------------------------------------------------------------------
# Context construction
# ---------------------------------------------------------------------------


def _collect_constants(ctx: FileContext) -> None:
    for stmt in ctx.tree.body:
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            continue
        target = stmt.targets[0]
        if not isinstance(target, ast.Name):
            continue
        value = stmt.value
        if isinstance(value, ast.Constant) and isinstance(value.value, int) \
                and not isinstance(value.value, bool):
            ctx.int_constants[target.id] = value.value
        elif isinstance(value, (ast.Tuple, ast.List)) and value.elts and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in value.elts
        ):
            ctx.str_tuple_constants[target.id] = tuple(
                e.value for e in value.elts
            )


def _kernel_name_from_arg(arg: ast.AST) -> str:
    """First argument of ``pallas_call``: a kernel name, possibly wrapped
    in ``functools.partial(kernel, ...)``."""
    if isinstance(arg, ast.Name):
        return arg.id
    if isinstance(arg, ast.Call) and is_partial_call(arg) and arg.args:
        inner = arg.args[0]
        if isinstance(inner, ast.Name):
            return inner.id
    return ""


def _smem_spec_indices(call: ast.Call) -> List[int]:
    """Positions in the ``pallas_call`` in_specs literal whose BlockSpec
    names an SMEM memory_space."""
    in_specs = next(
        (kw.value for kw in call.keywords if kw.arg == "in_specs"), None
    )
    if not isinstance(in_specs, (ast.List, ast.Tuple)):
        return []
    out = []
    for i, spec in enumerate(in_specs.elts):
        if not (isinstance(spec, ast.Call) and call_name(spec) == "BlockSpec"):
            continue
        space = next(
            (kw.value for kw in spec.keywords if kw.arg == "memory_space"),
            None,
        )
        if space is not None and dotted_name(space).rsplit(".", 1)[-1] == \
                "SMEM":
            out.append(i)
    return out


def _collect_kernels(ctx: FileContext) -> None:
    """Kernels = functions handed to ``pl.pallas_call`` — directly, via a
    ``functools.partial`` argument, or via a local name bound to such a
    partial inside a function that makes the ``pallas_call`` — plus, to a
    fixpoint, module functions that kernels call (helpers like
    ``_select_topk`` run inside the kernel too)."""
    module_funcs = {
        f.name: f for f in ctx.tree.body if isinstance(f, ast.FunctionDef)
    }
    names: Set[str] = set()
    for func in module_funcs.values():
        calls = [n for n in ast.walk(func) if isinstance(n, ast.Call)]
        if not any(call_name(c) == "pallas_call" for c in calls):
            continue
        ctx.has_pallas_call = True
        # local `kernel = functools.partial(_kernel_fn, ...)` bindings
        local_partials: Dict[str, str] = {}
        for node in ast.walk(func):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    isinstance(node.value, ast.Call) and \
                    is_partial_call(node.value) and node.value.args and \
                    isinstance(node.value.args[0], ast.Name):
                local_partials[node.targets[0].id] = node.value.args[0].id
        for c in calls:
            if call_name(c) == "pallas_call" and c.args:
                name = _kernel_name_from_arg(c.args[0])
                if isinstance(c.args[0], ast.Name):
                    name = local_partials.get(c.args[0].id, name)
                if name in module_funcs:
                    names.add(name)
                    # map SMEM in_specs positions to kernel param names:
                    # pallas kernels take (inputs..., outputs...,
                    # scratch...) positionally
                    params = [
                        a.arg for a in module_funcs[name].args.args
                    ]
                    smem = {
                        params[i]
                        for i in _smem_spec_indices(c)
                        if i < len(params)
                    }
                    if smem:
                        ctx.smem_params.setdefault(name, set()).update(smem)
            # a partial over a module function inside a pallas_call-
            # making function is (in this codebase's idiom) the kernel
            # being closed over its static params
            if is_partial_call(c) and c.args and isinstance(
                c.args[0], ast.Name
            ) and c.args[0].id in module_funcs:
                names.add(c.args[0].id)
    # transitive closure: helpers called from kernel bodies
    changed = True
    while changed:
        changed = False
        for name in list(names):
            for node in ast.walk(module_funcs[name]):
                if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Name
                ):
                    callee = node.func.id
                    if callee in module_funcs and callee not in names:
                        names.add(callee)
                        changed = True
    ctx.kernels = [module_funcs[n] for n in sorted(names)]


def lock_kind_of(node: ast.AST) -> str:
    """"lock"/"rlock"/... when ``node`` constructs a threading primitive
    (``threading.Lock()`` or a bare ``Lock()`` from-import); "" otherwise."""
    if not isinstance(node, ast.Call):
        return ""
    dn = dotted_name(node.func)
    tail = dn.rsplit(".", 1)[-1]
    if tail not in LOCK_KINDS:
        return ""
    if dn == tail or dn == f"threading.{tail}":
        return LOCK_KINDS[tail]
    return ""


def walk_in_scope(root: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk`` that does not descend into nested scopes (function /
    lambda / class definitions) — the concurrency rules analyze one
    execution scope at a time."""
    stack: List[ast.AST] = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                 ast.ClassDef),
            ):
                continue
            stack.append(child)


#: attribute methods that mutate their receiver — used both to infer
#: lock-guarded state (``self._items.append(x)`` under a lock) and to
#: spot request-time mutation of module-level registries
MUTATOR_METHODS = frozenset(
    {
        "append", "appendleft", "add", "update", "setdefault", "pop",
        "popleft", "popitem", "remove", "discard", "clear", "extend",
        "insert",
    }
)


def _self_attr(node: ast.AST) -> str:
    """``self.X`` → "X"; "" for anything else."""
    if isinstance(node, ast.Attribute) and isinstance(
        node.value, ast.Name
    ) and node.value.id == "self":
        return node.attr
    return ""


def _mutated_self_attrs(stmt: ast.AST) -> Set[str]:
    """self attrs this single statement writes: assignment/augassign
    targets (including ``self.X[k] = v``), ``del self.X[...]``, and
    mutator-method calls (``self.X.append(...)``)."""
    out: Set[str] = set()
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, ast.Delete):
        targets = list(stmt.targets)
    for t in targets:
        if isinstance(t, ast.Subscript):
            t = t.value
        attr = _self_attr(t)
        if attr:
            out.add(attr)
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        fn = stmt.value.func
        if isinstance(fn, ast.Attribute) and fn.attr in MUTATOR_METHODS:
            attr = _self_attr(fn.value)
            if attr:
                out.add(attr)
    return out


def _with_holds_self_mutex(stmt: ast.With, mutexes: Set[str]) -> bool:
    return any(
        _self_attr(item.context_expr) in mutexes for item in stmt.items
    )


def _collect_guarded_writes(cls: ClassScope) -> None:
    mutexes = cls.mutex_attrs()
    if not mutexes:
        return

    def visit(node: ast.AST, under: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                # a nested class has its own `self`: its writes belong
                # to ITS ClassScope, never this one
                continue
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
            ):
                # a nested scope runs later: an enclosing `with` does not
                # span its execution — restart the lock state inside it
                visit(child, False)
                continue
            held = under or (
                isinstance(child, ast.With)
                and _with_holds_self_mutex(child, mutexes)
            )
            if held:
                cls.guarded_writes |= _mutated_self_attrs(child)
            visit(child, held)

    visit(cls.node, False)


def _walk_skip_nested_classes(root: ast.ClassDef) -> Iterator[ast.AST]:
    """Walk a class body without descending into nested ClassDefs: a
    nested class has its own ``self``, so its assignments must not be
    attributed to the enclosing class (it gets its own ClassScope)."""
    stack: List[ast.AST] = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                continue
            stack.append(child)


def _collect_classes(ctx: FileContext) -> None:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        cls = ClassScope(
            node=node,
            name=node.name,
            is_thread_subclass=any(
                dotted_name(base) in ("threading.Thread", "Thread")
                for base in node.bases
            ),
        )
        for stmt in node.body:
            if isinstance(stmt, ast.FunctionDef):
                cls.methods[stmt.name] = stmt
        for sub in _walk_skip_nested_classes(node):
            if not isinstance(sub, ast.Assign) or len(sub.targets) != 1:
                continue
            attr = _self_attr(sub.targets[0])
            if not attr:
                continue
            kind = lock_kind_of(sub.value)
            if kind:
                cls.lock_attrs[attr] = kind
        _collect_guarded_writes(cls)
        ctx.classes.append(cls)


#: mutable-container constructors for module-registry tracking
_MUTABLE_CTORS = {
    "dict": "dict", "list": "list", "set": "set", "defaultdict": "dict",
    "OrderedDict": "dict", "deque": "deque", "Counter": "dict",
}


def _collect_module_state(ctx: FileContext) -> None:
    for stmt in ctx.tree.body:
        if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            continue
        if isinstance(stmt, ast.Assign):
            if len(stmt.targets) != 1 or not isinstance(
                stmt.targets[0], ast.Name
            ):
                continue
            name, value = stmt.targets[0].id, stmt.value
        else:
            if not isinstance(stmt.target, ast.Name) or stmt.value is None:
                continue
            name, value = stmt.target.id, stmt.value
        kind = lock_kind_of(value)
        if kind:
            ctx.module_locks[name] = kind
            continue
        if isinstance(value, ast.Call):
            dn = dotted_name(value.func)
            tail = dn.rsplit(".", 1)[-1]
            if tail == "ContextVar":
                ctx.module_contextvars.add(name)
                continue
            if tail in _MUTABLE_CTORS:
                ctx.module_mutables[name] = _MUTABLE_CTORS[tail]
                continue
        if isinstance(value, ast.Dict):
            ctx.module_mutables[name] = "dict"
        elif isinstance(value, ast.List):
            ctx.module_mutables[name] = "list"
        elif isinstance(value, ast.Set):
            ctx.module_mutables[name] = "set"


def _collect_suppressions(ctx: FileContext) -> None:
    """Collect suppressions from real COMMENT tokens only: the pattern
    inside a string literal (test sources, docs quoting the syntax) must
    never register as a reviewed exception."""
    try:
        tokens = list(
            tokenize.generate_tokens(io.StringIO(ctx.source).readline)
        )
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return  # unparseable files surface as parse errors elsewhere
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        rule_ids = {
            token.strip() for token in m.group(1).split(",") if token.strip()
        }
        ctx.suppressions.append(
            _Suppression(
                line=tok.start[0],
                rule_ids=rule_ids,
                reason=m.group(2),
                comment_only=not tok.line[: tok.start[1]].strip(),
            )
        )


def build_context(path: str, source: Optional[str] = None) -> FileContext:
    if source is None:
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
    tree = ast.parse(source, filename=path)
    ctx = FileContext(
        path=path,
        source=source,
        tree=tree,
        # absolute, so path-scoped rules (the serving hot-path suffix
        # match) see the same module identity however the file was named
        # on the command line (`pio lint serving.py` included)
        posix_path=os.path.abspath(path).replace(os.sep, "/"),
    )
    _collect_constants(ctx)
    _collect_kernels(ctx)
    _collect_classes(ctx)
    _collect_module_state(ctx)
    _collect_suppressions(ctx)
    return ctx


# ---------------------------------------------------------------------------
# Running rules + suppression application
# ---------------------------------------------------------------------------


def all_rules() -> List[Rule]:
    from . import (
        rules_conc,
        rules_flow,
        rules_jit,
        rules_mosaic,
        rules_obs,
        rules_robust,
        rules_spmd,
    )

    return [
        *rules_mosaic.RULES,
        *rules_jit.RULES,
        *rules_robust.RULES,
        *rules_obs.RULES,
        *rules_conc.RULES,
        *rules_spmd.RULES,
        *rules_flow.RULES,
    ]


def _split_rules(rules: Sequence[Rule]):
    """(per-file rules, package-scope flow rules)."""
    from .rules_flow import FlowRule

    file_rules = [r for r in rules if not isinstance(r, FlowRule)]
    flow_rules = [r for r in rules if isinstance(r, FlowRule)]
    return file_rules, flow_rules


def rules_signature(rules: Sequence[Rule]) -> str:
    """Cache key component: RULES_VERSION plus a digest of the
    registered (id, class) pairs — adding, removing, or re-homing a
    rule invalidates every cached verdict."""
    ids = ",".join(sorted({f"{r.id}/{type(r).__name__}" for r in rules}))
    digest = hashlib.sha256(ids.encode("utf-8")).hexdigest()[:16]
    return f"{RULES_VERSION}:{digest}"


@dataclasses.dataclass
class LintResult:
    files: int = 0
    #: unsuppressed findings — what the exit code and the gate count
    findings: List[Finding] = dataclasses.field(default_factory=list)
    #: suppressed findings, kept for reporting (``--format json``)
    suppressed: List[Finding] = dataclasses.field(default_factory=list)
    #: findings absorbed by an adopted baseline (``--baseline``): legacy
    #: debt that is acknowledged but not yet fixed — reported, not fatal
    baselined: List[Finding] = dataclasses.field(default_factory=list)
    #: files that failed to parse: (path, error)
    errors: List[tuple] = dataclasses.field(default_factory=list)
    #: what the engine actually did this run (cache hits, files parsed,
    #: files whose flow-* verdicts ran vs. came from cache) — the cache
    #: contract's observable surface; diagnostics, not part of the JSON
    #: document
    stats: Dict[str, object] = dataclasses.field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.findings and not self.errors


def _apply_suppressions(
    ctx: FileContext,
    raw: Iterable[Finding],
    active_rule_ids: Set[str],
) -> Iterator[Finding]:
    return _match_suppressions(
        ctx.path, ctx.suppressions, raw, active_rule_ids
    )


def _match_suppressions(
    path: str,
    suppressions: List[_Suppression],
    raw: Iterable[Finding],
    active_rule_ids: Set[str],
) -> Iterator[Finding]:
    by_line: Dict[int, List[_Suppression]] = {}
    for sup in suppressions:
        by_line.setdefault(sup.line, []).append(sup)
    for finding in sorted(raw, key=lambda f: (f.line, f.col, f.rule_id)):
        matched = None
        # same line, or a COMMENT-ONLY line directly above: a trailing
        # suppression covers its own line only, so one reviewed exception
        # can never silently absorb a second violation on the next line
        for line in (finding.line, finding.line - 1):
            for sup in by_line.get(line, ()):
                if finding.rule_id in sup.rule_ids and (
                    line == finding.line or sup.comment_only
                ):
                    matched = sup
                    break
            if matched:
                break
        if matched:
            matched.used = True
            yield dataclasses.replace(
                finding, suppressed=True, suppress_reason=matched.reason
            )
        else:
            yield finding
    # a suppression is a claim someone reviewed the exception; without a
    # reason the claim is unreviewable — and the self-lint gate requires
    # every suppression in the tree to justify itself
    for sup in suppressions:
        if not sup.reason:
            yield Finding(
                rule_id="lint-suppression-missing-reason",
                path=path,
                line=sup.line,
                col=1,
                message=(
                    "suppression without a reason: follow "
                    "'# pio: lint-ok[rule-id]' with a one-line "
                    "justification"
                ),
            )
        # a suppression whose rule ran but found nothing is stale: the
        # exception it reviewed is gone, and leaving the comment invites
        # readers to treat it as live. Only judged against rules that
        # actually ran, so --select can never manufacture staleness.
        elif not sup.used and sup.rule_ids & active_rule_ids:
            yield Finding(
                rule_id="lint-unused-suppression",
                path=path,
                line=sup.line,
                col=1,
                message=(
                    "unused suppression for "
                    f"{sorted(sup.rule_ids & active_rule_ids)}: no such "
                    "finding on this line — the exception it reviewed is "
                    "gone; delete the comment."
                ),
            )


def lint_file(
    path: str,
    rules: Optional[Sequence[Rule]] = None,
    source: Optional[str] = None,
) -> List[Finding]:
    """All findings for one file, suppressed ones included (marked)."""
    ctx = build_context(path, source=source)
    rules = list(rules) if rules is not None else all_rules()
    raw: List[Finding] = []
    for rule in rules:
        raw.extend(rule.check(ctx))
    return list(_apply_suppressions(ctx, raw, {r.id for r in rules}))


def iter_python_files(paths: Sequence[str]) -> Iterator[str]:
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        for root, dirs, files in os.walk(path):
            # prune hidden dirs (.git, .venv, .tox, ...) and vendored
            # trees: linting site-packages is never what the caller meant
            dirs[:] = sorted(
                d for d in dirs
                if not d.startswith(".")
                and d not in ("__pycache__", "_build", "node_modules",
                              "venv", "env", "site-packages")
            )
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


#: minimum per-file passes before a parallel run pays for its process
#: pool (fork + pickle overhead beats a serial parse below this)
_PARALLEL_MIN = 12


def _file_pass(path: str, module: str, select: Optional[Set[str]]):
    """One file's parse + per-file rules + fact extraction. Module-level
    and default-rule-set-only so it is picklable into a worker process.
    Returns (path, facts | None, raw per-file Findings | None, error)."""
    from . import packagectx
    from .rules_flow import FlowRule

    try:
        ctx = build_context(path)
    # SyntaxError: does not parse. ValueError: null bytes, and the
    # UnicodeDecodeError subclass for non-UTF8 files. OSError: file
    # vanished/unreadable mid-walk. All must be a recorded parse
    # error (and a nonzero exit), never a traceback that costs the
    # watcher its JSON document.
    except (SyntaxError, ValueError, OSError) as exc:
        return (path, None, None, f"{type(exc).__name__}: {exc}")
    rules = [r for r in all_rules() if not isinstance(r, FlowRule)]
    if select:
        rules = [r for r in rules if r.id in select]
    raw: List[Finding] = []
    for rule in rules:
        raw.extend(rule.check(ctx))
    return (path, packagectx.extract_facts(ctx, module), raw, None)


def _run_file_passes(
    todo: Sequence[str],
    modules: Dict[str, str],
    select: Optional[Set[str]],
    jobs: int,
    custom_file_rules: Optional[Sequence[Rule]],
) -> Dict[str, tuple]:
    """path → (facts, raw findings, error) for every file needing a
    fresh per-file pass; process-parallel when the batch is big enough
    (and the rule set is the picklable default), serial otherwise. Any
    pool failure falls back to the serial path — parallelism is a speed
    lever, never a correctness dependency."""
    out: Dict[str, tuple] = {}
    if custom_file_rules is None and jobs > 1 and len(todo) >= _PARALLEL_MIN:
        try:
            import concurrent.futures as cf

            with cf.ProcessPoolExecutor(
                max_workers=min(jobs, len(todo))
            ) as pool:
                futures = [
                    pool.submit(_file_pass, p, modules[p], select)
                    for p in todo
                ]
                for fut in futures:
                    path, facts, raw, err = fut.result()
                    out[path] = (facts, raw, err)
            return out
        except Exception:
            out = {}
    for path in todo:
        if custom_file_rules is None:
            _, facts, raw, err = _file_pass(path, modules[path], select)
            out[path] = (facts, raw, err)
            continue
        from . import packagectx

        try:
            ctx = build_context(path)
        except (SyntaxError, ValueError, OSError) as exc:
            out[path] = (None, None, f"{type(exc).__name__}: {exc}")
            continue
        raw = []
        for rule in custom_file_rules:
            raw.extend(rule.check(ctx))
        out[path] = (
            packagectx.extract_facts(ctx, modules[path]), raw, None
        )
    return out


def _load_cache(path: str) -> Optional[dict]:
    """Best-effort cache read: a missing, torn, or corrupt file is
    simply a cold sweep — never an error, never a different verdict."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or not isinstance(doc.get("files"), dict):
        return None
    return doc


def _save_cache(path: str, doc: dict) -> None:
    """Atomic best-effort write: a half-written cache must never exist
    for the next run to trust, and a read-only target dir must not fail
    the lint run that earned its verdict. Uses the packaged durable
    sequence — the fsync costs microseconds per run and retires the
    hand-rolled tmp+rename this function used to carry a lint
    suppression for."""
    try:
        atomic_write_bytes(path, json.dumps(doc).encode("utf-8"))
    except OSError:
        try:
            os.unlink(f"{path}.tmp")
        except OSError:
            pass


def _finding_from_dict(doc: dict, path: str) -> Finding:
    return Finding(
        rule_id=doc["rule"],
        path=path,
        line=doc["line"],
        col=doc["col"],
        message=doc["message"],
        severity=doc.get("severity", "error"),
    )


def lint_paths(
    paths: Sequence[str],
    rules: Optional[Sequence[Rule]] = None,
    select: Optional[Set[str]] = None,
    *,
    cache_path: Optional[str] = None,
    jobs: int = 0,
) -> LintResult:
    """Lint files/directories. ``select`` restricts to those rule ids.

    Two phases: a per-file pass (parse, families A–F, fact extraction —
    parallelizable across ``jobs`` worker processes) and a package pass
    (assemble :class:`~.packagectx.PackageContext` from the facts, run
    the ``flow-*`` rules). With ``cache_path`` set *and* the default
    rule set (no ``rules``/``select`` — partial rule sets must never
    write results a full run would trust), results are cached per file:

    - per-file findings + facts under the file's content hash;
    - ``flow-*`` findings under (content hash, hash of the transitive
      package-internal import closure's content hashes) — editing a
      helper invalidates exactly the flow verdicts of every file that
      can reach it through imports, and nothing else;
    - everything under :func:`rules_signature`, so a rules change
      invalidates the world. A stale cache can never suppress a
      finding: any mismatch falls back to a fresh judgment.

    ``result.stats`` reports what actually ran (``cache_hits``,
    ``parsed``, ``flow_ran``, ``flow_cached``) — the contract the cache
    tests pin."""
    from . import packagectx

    base = list(rules) if rules is not None else all_rules()
    if select:
        base = [r for r in base if r.id in select]
    file_rules, flow_rules = _split_rules(base)
    result = LintResult()
    # a target that does not exist must fail the run: the gate reading
    # exit 0 / ok=true as "lint-clean" must never get it from a typo'd
    # path that linted nothing
    missing = [p for p in paths if not os.path.exists(p)]
    for p in missing:
        result.errors.append((p, "no such file or directory"))
    paths = [p for p in paths if p not in missing]
    files = list(iter_python_files(paths))
    roots = [os.path.abspath(p) for p in paths if os.path.isdir(p)]
    modules = {p: packagectx.module_name_for(p, roots) for p in files}

    cache_ok = bool(cache_path) and rules is None and select is None
    sig = rules_signature(base)
    cache = _load_cache(cache_path) if cache_ok else None
    if cache is not None and cache.get("rules") != sig:
        cache = None
    cached_files: Dict[str, dict] = (cache or {}).get("files", {})

    hashes: Dict[str, str] = {}
    per_file: Dict[str, tuple] = {}
    hits: List[str] = []
    todo: List[str] = []
    for path in files:
        result.files += 1
        try:
            with open(path, "rb") as fh:
                data = fh.read()
        except OSError as exc:
            per_file[path] = (None, None, f"OSError: {exc}")
            continue
        hashes[path] = packagectx.content_hash(data)
        ent = cached_files.get(os.path.abspath(path))
        if (
            ent
            and ent.get("hash") == hashes[path]
            and isinstance(ent.get("facts"), dict)
            and isinstance(ent.get("local"), list)
        ):
            facts = dict(ent["facts"])
            facts["path"] = path  # display path of THIS run
            per_file[path] = (
                facts,
                [_finding_from_dict(d, path) for d in ent["local"]],
                None,
            )
            hits.append(path)
        else:
            todo.append(path)

    per_file.update(_run_file_passes(
        todo, modules, select, jobs,
        file_rules if rules is not None else None,
    ))

    table: Dict[str, dict] = {}
    hash_of_module: Dict[str, str] = {}
    for path in files:
        facts = per_file.get(path, (None, None, None))[0]
        if facts is not None:
            table[modules[path]] = facts
            hash_of_module[modules[path]] = hashes.get(path, "")
    pctx = packagectx.PackageContext(table)

    def deps_hash(module: str) -> str:
        closure = sorted(pctx.import_closure(module))
        blob = "|".join(
            f"{m}={hash_of_module.get(m, '?')}" for m in closure
        )
        return hashlib.sha256(blob.encode("utf-8")).hexdigest()

    active_ids = {r.id for r in base}
    flow_ran: List[str] = []
    flow_cached = 0
    new_files: Dict[str, dict] = {}
    for path in files:
        facts, raw_local, err = per_file.get(path, (None, None, None))
        if err is not None:
            result.errors.append((path, err))
            continue
        if facts is None:
            continue
        module = modules[path]
        dh = deps_hash(module)
        ent = cached_files.get(os.path.abspath(path))
        flow_doc = (ent or {}).get("flow") or {}
        if (
            path in hits
            and flow_doc.get("deps") == dh
            and isinstance(flow_doc.get("raw"), list)
        ):
            raw_flow = [
                _finding_from_dict(d, path) for d in flow_doc["raw"]
            ]
            flow_cached += 1
        else:
            raw_flow = []
            for rule in flow_rules:
                raw_flow.extend(rule.check_module(module, pctx))
            flow_ran.append(path)
        sups = [
            _Suppression(
                line=ln, rule_ids=set(ids), reason=reason,
                comment_only=comment_only,
            )
            for ln, ids, reason, comment_only in facts["suppressions"]
        ]
        for f in _match_suppressions(
            path, sups, list(raw_local) + raw_flow, active_ids
        ):
            (result.suppressed if f.suppressed else
             result.findings).append(f)
        if cache_ok:
            new_files[os.path.abspath(path)] = {
                "hash": hashes[path],
                "facts": facts,
                "local": [f.as_dict() for f in raw_local],
                "flow": {
                    "deps": dh,
                    "raw": [f.as_dict() for f in raw_flow],
                },
            }
    if cache_ok:
        _save_cache(cache_path, {
            "version": 1, "rules": sig, "files": new_files,
        })
    result.stats = {
        "cache_hits": len(hits),
        "parsed": sorted(todo),
        "flow_ran": sorted(flow_ran),
        "flow_cached": flow_cached,
    }
    return result


# ---------------------------------------------------------------------------
# Baseline (adopt/ratchet legacy findings)
# ---------------------------------------------------------------------------


def _baseline_key(path: str, rule_id: str) -> tuple:
    """Baseline bucket key. Paths are normalized relative to the current
    directory so a baseline recorded by CI matches a local run; keying on
    (path, rule) rather than (path, rule, line) keeps the baseline stable
    under unrelated edits that shift line numbers."""
    norm = os.path.relpath(os.path.abspath(path)).replace(os.sep, "/")
    return (norm, rule_id)


def load_baseline(path: str) -> Dict[tuple, int]:
    """Parse a baseline file into per-(path, rule) allowances. Accepts a
    full ``--format json`` document (its ``findings`` array) or a bare
    list of finding objects — so ``pio lint --format json > baseline.json``
    is the whole adoption workflow. Raises ValueError on anything else."""
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if isinstance(doc, dict):
        doc = doc.get("findings")
    if not isinstance(doc, list):
        raise ValueError(
            f"baseline {path}: expected a findings list or a "
            "`pio lint --format json` document"
        )
    counts: Dict[tuple, int] = {}
    for entry in doc:
        if not isinstance(entry, dict) or "rule" not in entry or \
                "path" not in entry:
            raise ValueError(
                f"baseline {path}: entries need 'rule' and 'path' keys"
            )
        key = _baseline_key(str(entry["path"]), str(entry["rule"]))
        counts[key] = counts.get(key, 0) + 1
    return counts


def apply_baseline(result: LintResult, counts: Dict[tuple, int]) -> None:
    """Move findings covered by the baseline into ``result.baselined``.

    Ratchet semantics per (path, rule) bucket: up to the baselined count
    is absorbed (oldest lines first — deterministic); anything beyond it
    is NEW debt and stays a failing finding. Buckets the current run no
    longer produces simply go unused — the baseline only ever shrinks."""
    remaining = dict(counts)
    kept: List[Finding] = []
    for f in sorted(result.findings, key=lambda f: (f.path, f.line, f.col)):
        key = _baseline_key(f.path, f.rule_id)
        if remaining.get(key, 0) > 0:
            remaining[key] -= 1
            result.baselined.append(f)
        else:
            kept.append(f)
    result.findings = kept


# ---------------------------------------------------------------------------
# Reporters
# ---------------------------------------------------------------------------


def render_text(result: LintResult) -> str:
    lines = []
    for path, err in result.errors:
        lines.append(f"{path}:1:1: [parse-error] {err}")
    for f in result.findings:
        lines.append(
            f"{f.path}:{f.line}:{f.col}: [{f.rule_id}] "
            f"{f.severity}: {f.message}"
        )
    summary = (
        f"{result.files} files, {len(result.findings)} findings, "
        f"{len(result.suppressed)} suppressed"
    )
    if result.baselined:
        summary += f", {len(result.baselined)} baselined"
    lines.append(summary)
    return "\n".join(lines)


def render_json(result: LintResult) -> str:
    return json.dumps(
        {
            "files": result.files,
            "findings": [f.as_dict() for f in result.findings],
            "suppressed": [f.as_dict() for f in result.suppressed],
            "baselined": [f.as_dict() for f in result.baselined],
            "errors": [
                {"path": p, "message": m} for p, m in result.errors
            ],
            "ok": result.ok,
        },
        indent=2,
    )
