"""Family B — jit-boundary hygiene rules, applied package-wide.

These catch the host/device boundary mistakes that don't break Mosaic
but quietly destroy serving latency or recompile per request: Python
control flow on traced values, ``jax.jit`` constructed inside loops,
host syncs on the serving hot path, import-time device arrays, and
unhashable static arguments.

Detection scope (stated in docs/lint.md): jit decoration is recognized
in decorator form — ``@jax.jit``, ``@jit``, and
``@functools.partial(jax.jit, ...)``. Call-form wrapping
(``f = jax.jit(g, ...)``, the als.py idiom) is out of scope for the
traced-branch rule; the jit-in-loop rule sees call-form uses anywhere.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Sequence, Set

from .engine import (
    STATIC_VALUE_ATTRS,
    FileContext,
    Finding,
    Rule,
    call_name,
    dotted_name,
    is_partial_call,
)

#: modules whose request path must never block on the device — the
#: serving hot path (ISSUE 1 scope; extend as hot paths are added)
HOT_PATH_SUFFIXES = (
    "workflow/serving.py",
    "workflow/batching.py",
)


def _is_jit_ref(node: ast.AST) -> bool:
    """``jax.jit`` / bare ``jit`` as a name reference."""
    return dotted_name(node) in ("jax.jit", "jit")


def _jit_static_params(
    func: ast.FunctionDef, ctx: FileContext
) -> Optional[Set[str]]:
    """None when ``func`` is not jit-decorated; otherwise the set of its
    static parameter names (resolved from static_argnames/static_argnums
    literals or module-level string-tuple constants)."""
    for dec in func.decorator_list:
        keywords: Sequence[ast.keyword] = ()
        if _is_jit_ref(dec):
            keywords = ()
        elif isinstance(dec, ast.Call) and _is_jit_ref(dec.func):
            keywords = dec.keywords
        elif (
            isinstance(dec, ast.Call)
            and is_partial_call(dec)
            and dec.args
            and _is_jit_ref(dec.args[0])
        ):
            keywords = dec.keywords
        else:
            continue
        static: Set[str] = set()
        params = [a.arg for a in func.args.posonlyargs + func.args.args]
        for kw in keywords:
            if kw.arg == "static_argnames":
                static |= set(_str_seq(kw.value, ctx) or ())
            elif kw.arg == "static_argnums":
                for num in _int_seq(kw.value, ctx) or ():
                    if 0 <= num < len(params):
                        static.add(params[num])
        # kwonly params named in static_argnames are covered by the set
        return static
    return None


def _str_seq(node: ast.AST, ctx: FileContext) -> Optional[List[str]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                return None
        return out
    if isinstance(node, ast.Name):
        seq = ctx.str_tuple_constants.get(node.id)
        return list(seq) if seq is not None else None
    return None


def _int_seq(node: ast.AST, ctx: FileContext) -> Optional[List[int]]:
    value = ctx.const_int(node)
    if value is not None:
        return [value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            v = ctx.const_int(e)
            if v is None:
                return None
            out.append(v)
        return out
    return None


def _traced_names_in_test(expr: ast.AST, traced: Set[str]) -> List[str]:
    """Parameter names used as traced VALUES in a branch test. Static
    facets (``x.shape``, ``x.dtype``, ``len(x)``, ``x is None``,
    ``isinstance(x, ...)``) don't count."""
    hits: List[str] = []

    def visit(node: ast.AST) -> None:
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_VALUE_ATTRS:
                return  # x.shape[...] etc. — static at trace time
            visit(node.value)
            return
        if isinstance(node, ast.Call):
            fname = call_name(node)
            if fname in ("len", "isinstance", "hasattr", "getattr", "type"):
                return
            for child in list(node.args) + [kw.value for kw in node.keywords]:
                visit(child)
            visit(node.func)
            return
        if isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
        ):
            return  # identity tests (x is None) are structural
        if isinstance(node, ast.Name):
            if node.id in traced:
                hits.append(node.id)
            return
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(expr)
    return hits


class PythonBranchOnTraced(Rule):
    """Python ``if``/``while`` on a traced argument inside ``@jit``
    raises ``TracerBoolConversionError`` at trace time — or worse, when
    the value is concrete on some call paths, silently bakes one branch
    into the compiled program. Use ``jnp.where``/``lax.cond``."""

    id = "jit-python-branch"
    severity = "error"
    short = "Python if/while on a traced argument inside a @jit function"
    motivation = (
        "the jit-boundary twin of the Mosaic control-flow rules: a "
        "branch that survives tracing only because today's callers pass "
        "concrete values is a recompile (or miscompile) waiting for the "
        "first traced caller"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            static = _jit_static_params(node, ctx)
            if static is None:
                continue
            params = {
                a.arg
                for a in (
                    node.args.posonlyargs + node.args.args
                    + node.args.kwonlyargs
                )
            }
            traced = params - static
            for stmt in ast.walk(node):
                if not isinstance(stmt, (ast.If, ast.While)):
                    continue
                hits = _traced_names_in_test(stmt.test, traced)
                if hits:
                    kind = "if" if isinstance(stmt, ast.If) else "while"
                    yield self.finding(
                        ctx,
                        stmt,
                        f"Python {kind!r} on traced argument(s) "
                        f"{sorted(set(hits))} inside @jit "
                        f"{node.name!r}: this fails (or specializes "
                        "wrongly) at trace time — use jnp.where / "
                        "lax.cond, or mark the argument static.",
                    )


class JitInLoop(Rule):
    """``jax.jit(...)`` constructed inside a loop body builds a fresh
    callable per iteration: every call re-traces and re-compiles, the
    compilation-cache win the serving path depends on evaporates."""

    id = "jit-in-loop"
    severity = "error"
    short = "jax.jit(...) constructed inside a for/while body"
    motivation = (
        "recompilation churn: the round-2 evidence priced one compile at "
        "2.67 s — per loop iteration, that is the whole hardware window"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in ast.walk(loop):
                if isinstance(node, ast.Call) and _is_jit_ref(node.func):
                    yield self.finding(
                        ctx,
                        node,
                        "jax.jit(...) constructed inside a loop body: each "
                        "iteration builds a fresh callable that re-traces "
                        "and re-compiles — hoist the jit out of the loop "
                        "(or functools.lru_cache the wrapper).",
                    )


class HostSyncInServing(Rule):
    """Host syncs on the serving hot path serialize the request on a
    device round trip: ``block_until_ready``, ``np.asarray``/
    ``np.array``, ``.item()``, and ``float(x[i])``-style scalar pulls
    all force the dispatch pipeline to drain. Scoped to the hot-path
    modules (``HOT_PATH_SUFFIXES``)."""

    id = "jit-host-sync-serving"
    severity = "warning"
    short = (
        "host sync (block_until_ready / np.asarray / .item() / "
        "float(x[i])) in a serving hot-path module"
    )
    motivation = (
        "the micro-batcher pipelines batch_pipeline_depth dispatches to "
        "hide the host-device round trip; one stray sync re-serializes "
        "all of it (docs/serving.md)"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.posix_path.endswith(HOT_PATH_SUFFIXES):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name == "block_until_ready":
                yield self.finding(
                    ctx, node,
                    "block_until_ready() on the serving hot path drains "
                    "the dispatch pipeline — let results resolve at "
                    "encode time.",
                )
            elif name in ("asarray", "array") and dotted_name(
                node.func
            ).split(".")[0] in ("np", "numpy", "onp"):
                yield self.finding(
                    ctx, node,
                    f"np.{name}() on the serving hot path synchronously "
                    "pulls the device buffer to host — keep values on "
                    "device until response encode.",
                )
            elif name == "item" and isinstance(node.func, ast.Attribute) \
                    and not node.args:
                yield self.finding(
                    ctx, node,
                    ".item() on the serving hot path is a blocking "
                    "device->host scalar pull.",
                )
            elif name in ("float", "int") and len(node.args) == 1 and \
                    isinstance(node.args[0], ast.Subscript):
                yield self.finding(
                    ctx, node,
                    f"{name}(x[...]) on the serving hot path pulls one "
                    "scalar per call from the device — batch the "
                    "conversion once per response instead.",
                )


class ModuleLevelDeviceArray(Rule):
    """A ``jnp.*`` call at module scope creates a device value (and
    initializes the backend) at import time — on whatever platform
    happens to be default — and jit closures then capture it as a baked
    constant that silently pins old data across reloads."""

    id = "jit-module-device-array"
    severity = "error"
    short = "module-level jnp.* / jax.device_put call (import-time device state)"
    motivation = (
        "the console deliberately propagates platform choice to children "
        "(utils/platform.py); an import-time jnp call defeats that by "
        "initializing the backend before configuration runs"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for stmt in ctx.tree.body:
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            value = stmt.value
            if value is None:
                continue
            for node in ast.walk(value):
                if not isinstance(node, ast.Call):
                    continue
                dn = dotted_name(node.func)
                if dn.startswith(("jnp.", "jax.numpy.")) or dn in (
                    "jax.device_put",
                ):
                    yield self.finding(
                        ctx,
                        node,
                        f"module-level {dn}(...) creates device state at "
                        "import time and gets captured by jit closures "
                        "as a baked constant — construct it lazily "
                        "inside the function (or as a plain Python "
                        "scalar/numpy value).",
                    )
                    break


class NonHashableStatic(Rule):
    """Static jit arguments are dict keys in the compilation cache: a
    parameter whose default is a list/dict/set (or that callers pass
    arrays into) raises ``Unhashable static arguments`` at call time —
    in production, on the first request that exercises the path."""

    id = "jit-nonhashable-static"
    severity = "error"
    short = (
        "static_argnames/static_argnums naming a parameter with a "
        "mutable (unhashable) default"
    )
    motivation = (
        "static args gate the serving dispatch cache; an unhashable one "
        "turns the first live query into a 500"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            static = _jit_static_params(node, ctx)
            if not static:
                continue
            args = node.args
            params = args.posonlyargs + args.args + args.kwonlyargs
            defaults: dict = {}
            pos = args.posonlyargs + args.args
            for param, default in zip(pos[len(pos) - len(args.defaults):],
                                      args.defaults):
                defaults[param.arg] = default
            for param, default in zip(args.kwonlyargs, args.kw_defaults):
                if default is not None:
                    defaults[param.arg] = default
            param_names = {p.arg for p in params}
            for name in sorted(static):
                if name not in param_names:
                    if args.kwarg is None:
                        yield self.finding(
                            ctx, node,
                            f"static_argnames names {name!r} which is not "
                            f"a parameter of {node.name!r} (typo?) — jit "
                            "raises at call time.",
                        )
                    continue
                default = defaults.get(name)
                if isinstance(
                    default, (ast.List, ast.Dict, ast.Set)
                ) or (
                    isinstance(default, ast.Call)
                    and call_name(default) in ("list", "dict", "set")
                ):
                    yield self.finding(
                        ctx, node,
                        f"static argument {name!r} of {node.name!r} has an "
                        "unhashable default: static args are hashed into "
                        "the compilation cache key — use a tuple/frozen "
                        "value.",
                    )


RULES = [
    PythonBranchOnTraced(),
    JitInLoop(),
    HostSyncInServing(),
    ModuleLevelDeviceArray(),
    NonHashableStatic(),
]
