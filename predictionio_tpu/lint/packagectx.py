"""Package-wide analysis layer: per-file fact tables + a one-level
call graph over them.

The per-file rules (families A–F) reason inside one
:class:`~predictionio_tpu.lint.engine.FileContext`; every one of them
ships a documented blind spot of the same shape — "the helper is
defined in another module, so the call is invisible". This module
closes that gap without giving up the engine's two properties:

- **stdlib-only** — ``ast`` + ``hashlib``; the linter must run where
  jax cannot import.
- **per-file incrementality** — a file's facts are a pure function of
  its source, expressed as JSON-serializable dicts (no AST nodes), so
  the engine can extract them in a worker process, cache them under a
  content hash, and rebuild the package view without re-parsing
  unchanged files.

:func:`extract_facts` boils one parsed file down to a fact dict:
function signatures, the blocking/collective calls each function makes
directly, the call sites each function issues (with the lock set held
at each site), class thread/lifecycle facts, the import table, and the
suppression comments. :class:`PackageContext` assembles the fact dicts
of every file in the lint scope and resolves call references through
the import table — direct calls, ``functools.partial`` locals,
``self.method`` through single-inheritance base classes — **one level
deep**. The flow rules (:mod:`rules_flow`) are judges over this
resolution; they never see an AST from another file.

Resolution contract (documented in docs/lint.md#family-g): a reference
that does not resolve to a function in the lint scope is *not judged*
— third-party and stdlib callees get the benefit of the doubt, exactly
like the per-file rules treat ``**kwargs`` splats.
"""

from __future__ import annotations

import ast
import hashlib
import os
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .engine import (
    FileContext,
    call_name,
    dotted_name,
    is_partial_call,
    walk_in_scope,
)

#: thread-constructor tails the thread-lifecycle facts track
_THREAD_CTORS = ("Thread", "Timer")

#: lifecycle method names (plus any ``stop*``-prefixed method) from
#: which a worker's stop/join story must be reachable
_LIFECYCLE_NAMES = frozenset(
    {"close", "server_close", "shutdown", "__exit__", "__del__"}
)


def is_lifecycle_method(name: str) -> bool:
    return name in _LIFECYCLE_NAMES or name.startswith("stop")


def module_name_for(path: str, roots: Sequence[str]) -> str:
    """Dotted module name for ``path`` given the directory targets of
    the lint run: ``<root>/fleet/router.py`` under root
    ``.../predictionio_tpu`` → ``predictionio_tpu.fleet.router`` (the
    root's basename is the package name, so absolute imports inside the
    package resolve). A file outside every root takes its package name
    from the ``__init__.py`` chain above it — ``--changed`` passes bare
    files, and naming them by stem alone would silently unresolve every
    absolute import between them — and only a file with no package at
    all is its bare stem."""
    abspath = os.path.abspath(path)
    for root in roots:
        root = os.path.abspath(root)
        if abspath == root or abspath.startswith(root + os.sep):
            rel = os.path.relpath(abspath, root)
            parts = rel.replace(os.sep, "/").split("/")
            parts[-1] = parts[-1][:-3]  # strip .py
            if parts[-1] == "__init__":
                parts.pop()
            return ".".join([os.path.basename(root)] + parts) or \
                os.path.basename(root)
    stem = os.path.basename(abspath)
    stem = stem[:-3] if stem.endswith(".py") else stem
    pkg_parts: List[str] = []
    d = os.path.dirname(abspath)
    while d and os.path.isfile(os.path.join(d, "__init__.py")):
        pkg_parts.insert(0, os.path.basename(d))
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    if pkg_parts:
        if stem == "__init__":
            return ".".join(pkg_parts)
        return ".".join(pkg_parts + [stem])
    return stem


def _is_thread_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    dn = dotted_name(node.func)
    tail = dn.rsplit(".", 1)[-1]
    return tail in _THREAD_CTORS and dn in (tail, f"threading.{tail}")


def _collect_imports(tree: ast.Module, module: str) -> Dict[str, str]:
    """Local name → dotted target. Targets are ``"a.b"`` (a module) or
    ``"a.b:sym"`` (a symbol of module ``a.b`` — which may itself turn
    out to be the submodule ``a.b.sym``; :class:`PackageContext`
    disambiguates against the actual module table at resolve time).
    Relative imports are resolved against ``module``'s package."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    out[alias.asname] = alias.name
                else:
                    # `import a.b.c` binds `a`; dotted uses walk from it
                    out[alias.name.split(".")[0]] = alias.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                # level 1 = the containing package, each extra level one up
                parts = module.split(".")
                cut = len(parts) - node.level
                if cut < 0:
                    continue
                base = ".".join(parts[:cut])
                if node.module:
                    base = f"{base}.{node.module}" if base else node.module
            else:
                base = node.module or ""
            if not base:
                continue
            for alias in node.names:
                if alias.name == "*":
                    continue
                out[alias.asname or alias.name] = f"{base}:{alias.name}"
    return out


def _import_modules(imports: Dict[str, str]) -> List[str]:
    """Candidate module dependencies for an import table. A
    ``"mod:sym"`` target contributes BOTH ``mod`` and ``mod.sym``:
    ``from pkg import sub`` binds a submodule that call resolution will
    follow (``_resolve_import`` promotes it), so the dependency set that
    keys flow caching and the ``--changed`` reverse closure must cover
    it too — a candidate that turns out not to be a module just fails
    to resolve in ``internal_imports``. The resolver and the dependency
    set must never disagree: an edge the resolver can follow but the
    deps miss is a stale cached verdict waiting to suppress a finding."""
    out: Set[str] = set()
    for target in imports.values():
        mod, _, sym = target.partition(":")
        out.add(mod)
        if sym:
            out.add(f"{mod}.{sym}")
    return sorted(out)


def _call_ref(
    call: ast.Call,
    module_funcs: Set[str],
    partials: Dict[str, Tuple[str, int]],
) -> Tuple[str, int]:
    """(reference string, prebound-positional-count) for a call site,
    or ("", 0) when the callee is not a resolvable shape (a call on an
    arbitrary expression)."""
    fn = call.func
    if isinstance(fn, ast.Name):
        if fn.id in partials:
            ref, bound = partials[fn.id]
            return ref, bound
        if fn.id in module_funcs:
            return f"local:{fn.id}", 0
        return f"name:{fn.id}", 0
    if isinstance(fn, ast.Attribute):
        if isinstance(fn.value, ast.Name) and fn.value.id == "self":
            return f"self:{fn.attr}", 0
        dn = dotted_name(fn)
        if dn:
            return f"dotted:{dn}", 0
    return "", 0


def _local_partials(
    fn: ast.AST,
    module_funcs: Set[str],
) -> Dict[str, Tuple[str, int]]:
    """``cb = functools.partial(helper, a, b)`` locals: name →
    (reference to the wrapped callable, count of prebound positionals).
    A later ``cb(...)`` call then resolves through the partial — the
    call-graph edge the tentpole names."""
    out: Dict[str, Tuple[str, int]] = {}
    for node in walk_in_scope(fn):
        if not (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Call)
            and is_partial_call(node.value)
            and node.value.args
        ):
            continue
        inner = node.value.args[0]
        bound = len(node.value.args) - 1
        if isinstance(inner, ast.Name):
            ref = (
                f"local:{inner.id}" if inner.id in module_funcs
                else f"name:{inner.id}"
            )
            out[node.targets[0].id] = (ref, bound)
        elif isinstance(inner, ast.Attribute):
            if isinstance(inner.value, ast.Name) and inner.value.id == "self":
                out[node.targets[0].id] = (f"self:{inner.attr}", bound)
            else:
                dn = dotted_name(inner)
                if dn:
                    out[node.targets[0].id] = (f"dotted:{dn}", bound)
    return out


def _iter_with_lockstate(
    root: ast.AST, holds
) -> Iterator[Tuple[ast.AST, Set[str]]]:
    """(node, held-lock-labels) over one execution scope; nested
    function/class bodies restart with an empty lock set (an enclosing
    ``with`` wraps their definition, not their execution)."""

    def visit(node: ast.AST, held: Set[str]) -> Iterator:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                 ast.ClassDef),
            ):
                continue  # their calls are extracted via their own facts
            now = held
            if isinstance(child, ast.With):
                got = holds(child)
                if got:
                    now = held | got
            yield child, now
            yield from visit(child, now)

    yield from visit(root, set())


def _self_attr_of(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute) and isinstance(
        node.value, ast.Name
    ) and node.value.id == "self":
        return node.attr
    return ""


_DEADLINE_FACTORIES = frozenset({"from_header", "after_ms"})


def _acquires_deadline(fn: ast.AST) -> bool:
    """True when the function's body binds or scopes a deadline: an
    assignment from ``current_deadline()`` / ``Deadline.from_header`` /
    ``Deadline.after_ms``, or a ``with deadline_scope(...)`` block."""
    for node in walk_in_scope(fn):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name == "current_deadline":
                return True
            if name in _DEADLINE_FACTORIES and \
                    "Deadline" in dotted_name(node.func):
                return True
        if isinstance(node, ast.With):
            for item in node.items:
                if isinstance(item.context_expr, ast.Call) and \
                        call_name(item.context_expr) == "deadline_scope":
                    return True
    return False


def _collective_fact(
    call: ast.Call, vararg: Optional[str], kwarg: Optional[str]
) -> Optional[dict]:
    from . import rules_spmd

    if not rules_spmd._is_collective(call):
        return None
    axis_idx = rules_spmd._COLLECTIVES[call_name(call)]
    pre = sum(1 for a in call.args if not isinstance(a, ast.Starred))
    # an axis is statically present only via axis_name= or a real (non-
    # Starred) positional in the axis slot — _collective_axis_arg would
    # count a `*args` splat AT the slot as an axis, which is exactly
    # the case this fact exists to judge at call sites
    has_axis = any(
        kw.arg == "axis_name" for kw in call.keywords
    ) or (
        not any(isinstance(a, ast.Starred) for a in call.args)
        and len(call.args) > axis_idx
    )
    splat_own = any(
        isinstance(a, ast.Starred)
        and isinstance(a.value, ast.Name)
        and vararg is not None
        and a.value.id == vararg
        for a in call.args
    ) or any(
        kw.arg is None
        and isinstance(kw.value, ast.Name)
        and kwarg is not None
        and kw.value.id == kwarg
        for kw in call.keywords
    )
    other_splat = (
        any(isinstance(a, ast.Starred) for a in call.args)
        or any(kw.arg is None for kw in call.keywords)
    ) and not splat_own
    return {
        "name": dotted_name(call.func),
        "line": call.lineno,
        # ok: axis statically present, OR splatted from something that
        # is not the enclosing function's own *args/**kwargs (benefit
        # of the doubt — not statically knowable even via call sites)
        "ok": has_axis or other_splat,
        # vararg: the axis slot can only be filled by the enclosing
        # function's own *args/**kwargs — judged at its call sites
        "vararg": splat_own and not has_axis and pre <= axis_idx,
    }


def _function_facts(
    fn: ast.FunctionDef,
    cls_name: Optional[str],
    ctx: FileContext,
    module_funcs: Set[str],
    class_locks: Dict[str, str],
) -> dict:
    from . import rules_conc

    args = fn.args
    params = [a.arg for a in args.posonlyargs + args.args]
    if cls_name and params and params[0] in ("self", "cls"):
        params = params[1:]
    kwonly = [a.arg for a in args.kwonlyargs]
    kwonly_defaulted = [
        a.arg
        for a, d in zip(args.kwonlyargs, args.kw_defaults)
        if d is not None
    ]
    mutexes = {
        attr for attr, kind in class_locks.items()
        if kind in ("lock", "rlock", "condition")
    }

    def holds(w: ast.With) -> Set[str]:
        got: Set[str] = set()
        for item in w.items:
            expr = item.context_expr
            attr = _self_attr_of(expr)
            if attr and attr in mutexes:
                got.add(f"self.{attr}")
            elif isinstance(expr, ast.Name) and ctx.module_locks.get(
                expr.id
            ) in ("lock", "rlock", "condition"):
                got.add(expr.id)
        return got

    partials = _local_partials(fn, module_funcs)
    calls: List[dict] = []
    blocking: List[List] = []
    collectives: List[dict] = []
    ambient = False
    self_reads: Set[str] = set()
    event_sets: Set[str] = set()
    joins: Set[str] = set()
    # `for t in self._threads:` iteration vars, so `t.join()` counts as
    # joining the attr
    iter_vars: Dict[str, str] = {}
    for node in walk_in_scope(fn):
        if isinstance(node, ast.For) and isinstance(node.target, ast.Name):
            attr = _self_attr_of(node.iter)
            if attr:
                iter_vars[node.target.id] = attr
    for node, held in _iter_with_lockstate(fn, holds):
        attr = _self_attr_of(node)
        if attr:
            self_reads.add(attr)
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name == "current_deadline":
            ambient = True
        shown = rules_conc._is_blocking_call(node)
        if shown:
            blocking.append([shown, node.lineno])
        cfact = _collective_fact(node, args.vararg and args.vararg.arg,
                                 args.kwarg and args.kwarg.arg)
        if cfact is not None:
            collectives.append(cfact)
        if isinstance(node.func, ast.Attribute):
            recv_attr = _self_attr_of(node.func.value)
            if node.func.attr == "set" and recv_attr:
                event_sets.add(recv_attr)
            if node.func.attr == "join":
                if recv_attr:
                    joins.add(recv_attr)
                elif isinstance(node.func.value, ast.Name) and \
                        node.func.value.id in iter_vars:
                    joins.add(iter_vars[node.func.value.id])
        ref, bound = _call_ref(node, module_funcs, partials)
        if ref:
            calls.append({
                "line": node.lineno,
                "col": node.col_offset + 1,
                "ref": ref,
                "nargs": bound + sum(
                    1 for a in node.args if not isinstance(a, ast.Starred)
                ),
                "star": any(
                    isinstance(a, ast.Starred) for a in node.args
                ),
                "kwsplat": any(kw.arg is None for kw in node.keywords),
                "kws": sorted(
                    kw.arg for kw in node.keywords if kw.arg is not None
                ),
                "locks": sorted(held),
            })
    return {
        "name": fn.name,
        "line": fn.lineno,
        "cls": cls_name,
        "params": params,
        "defaults": len(args.defaults),
        "kwonly": kwonly,
        "kwonly_defaulted": kwonly_defaulted,
        "vararg": bool(args.vararg),
        "kwarg": bool(args.kwarg),
        "has_deadline": (
            "deadline" in params
            or "deadline" in kwonly
            or _acquires_deadline(fn)
        ),
        "ambient_deadline": ambient,
        "blocking": blocking,
        "collectives": collectives,
        "calls": calls,
        "self_reads": sorted(self_reads),
        "event_sets": sorted(event_sets),
        "joins": sorted(joins),
    }


def _class_thread_attrs(node: ast.ClassDef) -> Tuple[List[List], bool]:
    """(thread-holding self attrs [[attr, line], ...], started?) for one
    class: direct ``self.X = Thread(...)``, a list literal/comprehension
    of thread constructors, and the ``t = Thread(...); self.X.append(t)``
    idiom. ``started`` is a cheap class-wide gate: some ``.start()``
    call exists (a constructed-but-never-started worker can't leak)."""
    threads: Dict[str, int] = {}
    started = False
    local_threads: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.ClassDef) and sub is not node:
            continue
        if isinstance(sub, ast.Call) and isinstance(
            sub.func, ast.Attribute
        ) and sub.func.attr == "start":
            started = True
        if not isinstance(sub, ast.Assign) or len(sub.targets) != 1:
            continue
        target, value = sub.targets[0], sub.value
        attr = _self_attr_of(target)
        if attr:
            if _is_thread_ctor(value):
                threads.setdefault(attr, sub.lineno)
            elif isinstance(value, (ast.List, ast.Tuple)) and any(
                _is_thread_ctor(e) for e in value.elts
            ):
                threads.setdefault(attr, sub.lineno)
            elif isinstance(value, ast.ListComp) and _is_thread_ctor(
                value.elt
            ):
                threads.setdefault(attr, sub.lineno)
        elif isinstance(target, ast.Name) and _is_thread_ctor(value):
            local_threads.add(target.id)
    if local_threads:
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "append"
                and sub.args
                and isinstance(sub.args[0], ast.Name)
                and sub.args[0].id in local_threads
            ):
                attr = _self_attr_of(sub.func.value)
                if attr:
                    threads.setdefault(attr, sub.lineno)
    return [[a, ln] for a, ln in sorted(threads.items())], started


def extract_facts(ctx: FileContext, module: str) -> dict:
    """One file's flow-relevant facts as a JSON-serializable dict — the
    unit the incremental cache stores and worker processes ship back."""
    module_funcs = {
        f.name for f in ctx.tree.body if isinstance(f, ast.FunctionDef)
    }
    functions: Dict[str, dict] = {}
    classes: Dict[str, dict] = {}
    for stmt in ctx.tree.body:
        if isinstance(stmt, ast.FunctionDef):
            functions[stmt.name] = _function_facts(
                stmt, None, ctx, module_funcs, {}
            )
    for cs in ctx.classes:
        threads, started = _class_thread_attrs(cs.node)
        classes[cs.name] = {
            "name": cs.name,
            "line": cs.node.lineno,
            "bases": [
                dotted_name(b) for b in cs.node.bases if dotted_name(b)
            ],
            "methods": sorted(cs.methods),
            "locks": dict(cs.lock_attrs),
            "threads": threads,
            "started": started,
            "thread_subclass": cs.is_thread_subclass,
        }
        for name, meth in cs.methods.items():
            functions[f"{cs.name}.{name}"] = _function_facts(
                meth, cs.name, ctx, module_funcs, cs.lock_attrs
            )
    mapped: List[dict] = []
    for node in ast.walk(ctx.tree):
        if not (
            isinstance(node, ast.Call)
            and call_name(node) in ("shard_map", "pmap")
            and node.args
        ):
            continue
        fn = node.args[0]
        if isinstance(fn, ast.Call) and is_partial_call(fn) and fn.args:
            fn = fn.args[0]
        ref = ""
        if isinstance(fn, ast.Name):
            ref = (
                f"local:{fn.id}" if fn.id in module_funcs
                else f"name:{fn.id}"
            )
        elif isinstance(fn, ast.Attribute):
            dn = dotted_name(fn)
            if dn:
                ref = f"dotted:{dn}"
        if ref:
            mapped.append({"line": node.lineno, "ref": ref})
    imports = _collect_imports(ctx.tree, module)
    import_modules = _import_modules(imports)
    return {
        "module": module,
        "path": ctx.path,
        "imports": imports,
        "import_modules": sorted(import_modules),
        "functions": functions,
        "classes": classes,
        "mapped": mapped,
        "suppressions": [
            [s.line, sorted(s.rule_ids), s.reason, s.comment_only]
            for s in ctx.suppressions
        ],
    }


class PackageContext:
    """The assembled package view: fact dicts for every module in the
    lint scope, plus the resolution machinery (imports, one-level call
    graph, single-inheritance method resolution) the flow rules judge
    against."""

    #: resolution depth cap for base-class chains (defensive: a base
    #: cycle in analyzed code must not hang the linter)
    _MAX_CHAIN = 8

    def __init__(self, facts_by_module: Dict[str, dict]):
        self.modules = facts_by_module
        # unambiguous tail-component index: lets a single-file or
        # fixture-dir run resolve `from helper import f` even though
        # its modules are rooted at the target dir's basename
        tails: Dict[str, Optional[str]] = {}
        for mod in facts_by_module:
            tail = mod.rsplit(".", 1)[-1]
            tails[tail] = None if tail in tails else mod
            if mod not in tails:
                tails[mod] = mod
        self._by_tail = {t: m for t, m in tails.items() if m}

    # -- module / import resolution ------------------------------------

    def _module(self, dotted: str) -> Optional[str]:
        if dotted in self.modules:
            return dotted
        hit = self._by_tail.get(dotted)
        if hit:
            return hit
        # suffix match: `predictionio_tpu.fleet.router` target seen
        # from a run rooted deeper/shallower
        for mod in self.modules:
            if mod.endswith("." + dotted):
                return mod
        return None

    def _resolve_import(
        self, module: str, name: str
    ) -> Optional[Tuple[str, str]]:
        """Resolve local ``name`` in ``module`` through its import
        table → ("module", "") for a module binding or
        ("module", "symbol") for a symbol binding; None when the import
        leaves the lint scope."""
        facts = self.modules.get(module)
        if facts is None:
            return None
        target = facts["imports"].get(name)
        if target is None:
            return None
        if ":" in target:
            mod, sym = target.split(":", 1)
            # `from a.b import m` where a.b.m is a module in scope
            as_module = self._module(f"{mod}.{sym}")
            if as_module:
                return (as_module, "")
            base = self._module(mod)
            if base:
                return (base, sym)
            return None
        base = self._module(target)
        return (base, "") if base else None

    # -- call resolution (the one-level call graph) --------------------

    def resolve_call(
        self, module: str, cls: Optional[str], ref: str
    ) -> Optional[Tuple[str, str, dict]]:
        """Resolve one call reference from (module, enclosing class) to
        (callee module, callee qualname, callee function facts), or
        None when the callee is outside the lint scope. This is the
        whole call-graph contract: exactly one resolution hop — the
        callee's own calls are facts, not edges to chase further."""
        facts = self.modules.get(module)
        if facts is None or not ref:
            return None
        kind, _, rest = ref.partition(":")
        if kind == "local":
            fn = facts["functions"].get(rest)
            return (module, rest, fn) if fn else None
        if kind == "self":
            if cls is None:
                return None
            return self.resolve_method(module, cls, rest)
        if kind == "name":
            hit = self._resolve_import(module, rest)
            if hit is None:
                return None
            mod, sym = hit
            if not sym:
                return None  # a bare module is not callable here
            fn = self.modules[mod]["functions"].get(sym)
            return (mod, sym, fn) if fn else None
        if kind == "dotted":
            return self._resolve_dotted(module, rest)
        return None

    def _resolve_dotted(
        self, module: str, dotted: str
    ) -> Optional[Tuple[str, str, dict]]:
        facts = self.modules[module]
        parts = dotted.split(".")
        head, rest = parts[0], parts[1:]
        # `SomeClass.method(...)` on a same-module class
        if head in facts["classes"] and len(rest) == 1:
            fn = facts["functions"].get(f"{head}.{rest[0]}")
            return (module, f"{head}.{rest[0]}", fn) if fn else None
        hit = self._resolve_import(module, head)
        if hit is None:
            return None
        mod, sym = hit
        if sym:
            # imported class: `Cls.method(...)`
            if len(rest) == 1 and sym in self.modules[mod]["classes"]:
                fn = self.modules[mod]["functions"].get(f"{sym}.{rest[0]}")
                return (mod, f"{sym}.{rest[0]}", fn) if fn else None
            return None
        # walk module path as deep as the module table allows
        while len(rest) > 1:
            deeper = self._module(f"{mod}.{rest[0]}")
            if deeper is None:
                break
            mod, rest = deeper, rest[1:]
        if len(rest) == 1:
            fn = self.modules[mod]["functions"].get(rest[0])
            if fn:
                return (mod, rest[0], fn)
        if len(rest) == 2:
            fn = self.modules[mod]["functions"].get(f"{rest[0]}.{rest[1]}")
            if fn:
                return (mod, f"{rest[0]}.{rest[1]}", fn)
        return None

    # -- class machinery -----------------------------------------------

    def _resolve_class(
        self, module: str, dotted: str
    ) -> Optional[Tuple[str, str]]:
        facts = self.modules.get(module)
        if facts is None:
            return None
        if dotted in facts["classes"]:
            return (module, dotted)
        parts = dotted.split(".")
        hit = self._resolve_import(module, parts[0])
        if hit is None:
            return None
        mod, sym = hit
        if sym and not parts[1:]:
            if sym in self.modules[mod]["classes"]:
                return (mod, sym)
            return None
        if not sym and len(parts) == 2:
            if parts[1] in self.modules[mod]["classes"]:
                return (mod, parts[1])
        return None

    def class_chain(
        self, module: str, cls: str
    ) -> Iterator[Tuple[str, str, dict]]:
        """(module, class name, class facts) for ``cls`` and its
        package-resolvable single-inheritance ancestors — first base
        only, the documented resolution contract."""
        seen: Set[Tuple[str, str]] = set()
        cur: Optional[Tuple[str, str]] = (module, cls)
        for _ in range(self._MAX_CHAIN):
            if cur is None or cur in seen:
                return
            seen.add(cur)
            mod, name = cur
            facts = self.modules.get(mod)
            if facts is None:
                return
            cfacts = facts["classes"].get(name)
            if cfacts is None:
                return
            yield mod, name, cfacts
            bases = cfacts.get("bases") or []
            cur = self._resolve_class(mod, bases[0]) if bases else None

    def resolve_method(
        self, module: str, cls: str, method: str
    ) -> Optional[Tuple[str, str, dict]]:
        for mod, name, _cfacts in self.class_chain(module, cls):
            fn = self.modules[mod]["functions"].get(f"{name}.{method}")
            if fn:
                return (mod, f"{name}.{method}", fn)
        return None

    # -- import graph (cache invalidation + --changed closure) ---------

    def internal_imports(self, module: str) -> List[str]:
        facts = self.modules.get(module)
        if facts is None:
            return []
        out = []
        for dep in facts["import_modules"]:
            hit = self._module(dep)
            if hit and hit != module:
                out.append(hit)
        return sorted(set(out))

    def import_closure(self, module: str) -> Set[str]:
        """Transitive package-internal import closure, ``module``
        included — the dependency set whose content hashes key a flow
        result in the incremental cache."""
        out: Set[str] = set()
        stack = [module]
        while stack:
            mod = stack.pop()
            if mod in out:
                continue
            out.add(mod)
            stack.extend(self.internal_imports(mod))
        return out

    def reverse_importers(self, module: str) -> Set[str]:
        """Modules whose transitive import closure contains ``module``
        (itself included) — the re-lint scope when ``module`` changes."""
        return {
            mod for mod in self.modules
            if module in self.import_closure(mod)
        }


def single_file_context(ctx: FileContext) -> Tuple[str, "PackageContext"]:
    """PackageContext over just one parsed file (``lint_file`` — fixture
    twins, editor integrations). Cached on the FileContext so the flow
    rules share one extraction."""
    cached = getattr(ctx, "_pkg_single", None)
    if cached is not None:
        return cached
    module = module_name_for(ctx.path, [])
    facts = extract_facts(ctx, module)
    pctx = PackageContext({module: facts})
    ctx._pkg_single = (module, pctx)  # type: ignore[attr-defined]
    return module, pctx


def content_hash(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def reverse_closure_paths(
    scope_dirs: Sequence[str], changed: Sequence[str]
) -> List[str]:
    """The ``--changed`` cross-file closure: package files under
    ``scope_dirs`` whose transitive imports reach a changed file — the
    files whose ``flow-*`` verdicts the edit may have flipped. Parses
    import statements only; a file that fails to parse is simply not
    pulled in (it will fail loudly when it is itself linted)."""
    from .engine import iter_python_files

    roots = [os.path.abspath(d) for d in scope_dirs if os.path.isdir(d)]
    if not roots:
        return []
    table: Dict[str, dict] = {}
    path_of: Dict[str, str] = {}
    for path in iter_python_files(roots):
        module = module_name_for(path, roots)
        try:
            with open(path, "r", encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), filename=path)
        except (SyntaxError, ValueError, OSError):
            continue
        imports = _collect_imports(tree, module)
        table[module] = {
            "module": module,
            "path": path,
            "imports": imports,
            "import_modules": _import_modules(imports),
            "functions": {},
            "classes": {},
            "mapped": [],
            "suppressions": [],
        }
        path_of[module] = path
    pctx = PackageContext(table)
    changed_abs = {os.path.abspath(p) for p in changed}
    changed_mods = {
        m for m, p in path_of.items() if os.path.abspath(p) in changed_abs
    }
    out: Set[str] = set()
    for target in changed_mods:
        for mod in pctx.reverse_importers(target):
            if os.path.abspath(path_of[mod]) not in changed_abs:
                out.add(path_of[mod])
    return sorted(out)
