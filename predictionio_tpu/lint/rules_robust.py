"""Family C — robustness hygiene rules, applied package-wide.

The resilience layer (``utils/resilience.py``, ISSUE 2) only holds if
new code keeps its discipline: every network call bounded by a timeout,
every retry loop jittered. Both failure shapes are mechanical, so — like
the Mosaic and jit families — they are caught at AST level, before the
first incident:

- ``robust-no-timeout``: a network call with no explicit timeout is an
  unbounded hang waiting for a half-dead peer; one stalled dependency
  then wedges a handler thread (or the whole feedback pool) forever.
- ``robust-bare-sleep-retry``: a retry loop that sleeps a constant
  synchronizes every failing client into a thundering herd — the exact
  pathology full-jitter backoff (``RetryPolicy``) exists to kill.
- ``robust-rename-no-fsync`` (ISSUE 3): write-then-``os.replace`` with
  no fsync in the same scope leaves a durable *name* over torn *data*
  after a power loss — the bug class ``testing/crashsim.py`` proves and
  ``utils/durability.py`` packages the fix for.
- ``robust-unbounded-retry`` (ISSUE 13): a ``while True`` retry loop
  whose except handler swallows and re-iterates, with no attempt cap,
  no conditional exit (deadline check) and no backoff — against a dead
  dependency it spins forever at full speed, pinning a CPU and
  hammering the recovering peer; the partitioned write path's whole
  point is that a dead partition sheds *boundedly*
  (``RetryPolicy`` + ``PartitionUnavailable``).
- ``robust-unbounded-cache`` (ISSUE 14): a dict/OrderedDict named like
  a cache, written get-then-set on request-derived keys with no
  eviction bound in scope — a slow OOM whose growth rate the client
  controls; ``fleet/cache.py``'s ``ResponseCache`` (bounded LRU + TTL +
  epoch invalidation) is the packaged fix.
- ``robust-cutover-no-watermark`` (ISSUE 17): a cutover-named function
  that flips a read/write path between two stores/layouts (the same
  target assigned one source per branch) with no drain/watermark/
  barrier evidence anywhere in scope — flipping without verifying the
  lagging side strands every write still in flight on a path nothing
  reads anymore; ``storage/migration.py``'s ``cutover`` (freeze →
  final drain → per-keyspace watermark → flip) is the packaged shape.
- ``robust-nonatomic-checkpoint`` (ISSUE 20): a checkpoint/save/
  persist-marked function that writes files with no atomicity evidence
  in scope (no ``atomic_*`` helper, no rename+fsync sequence) — a crash
  mid-write leaves a half-written file under the real name, which the
  next run loads as a valid checkpoint; ``ckpt/store.py``'s
  per-file ``atomic_write_bytes`` + manifest-last commit is the
  packaged shape.
- ``robust-fallback-swallows`` (ISSUE 18): a fallback/degrade-marked
  except handler that discards the primary's failure without recording
  it anywhere (no log/counter call, the bound exception never read) —
  the degrade path works, so nothing pages, and the primary stays
  silently dead until the fallback ALSO fails;
  ``fleet/sharedcache.py``'s ``_record_degrade`` (count + last_error +
  debug log, THEN return the advisory miss) is the packaged shape.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional

from .engine import FileContext, Finding, Rule, call_name, dotted_name

#: requests.<verb>(...) — the high-level HTTP client surface
_REQUESTS_VERBS = frozenset(
    {"get", "post", "put", "patch", "delete", "head", "options", "request"}
)

#: (callable name, index of the positional slot that carries the timeout,
#: or None when the API takes it as keyword-only in practice)
_TIMEOUT_POSITIONS = {
    "urlopen": 2,  # urllib.request.urlopen(url, data, timeout)
    "HTTPConnection": 2,  # http.client.HTTPConnection(host, port, timeout=..)
    "HTTPSConnection": 2,
    "create_connection": 1,  # socket.create_connection(address, timeout)
}


def _has_timeout(node: ast.Call, positional_slot: Optional[int]) -> bool:
    if any(kw.arg == "timeout" for kw in node.keywords):
        return True
    # a **kwargs splat may carry it — give the benefit of the doubt
    if any(kw.arg is None for kw in node.keywords):
        return True
    return positional_slot is not None and len(node.args) > positional_slot


class NoTimeout(Rule):
    """Network call without an explicit timeout: the stdlib and requests
    default to *blocking forever*, so a peer that accepts the connection
    and then stalls holds the calling thread for good."""

    id = "robust-no-timeout"
    severity = "error"
    short = (
        "network call (requests.*/urlopen/HTTPConnection/"
        "create_connection) without an explicit timeout"
    )
    motivation = (
        "the pre-ISSUE-2 serving path hung indefinitely on a stalled "
        "Event Server because nothing bounded the socket wait; a "
        "timeout is the floor of every other resilience primitive"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            name = call_name(node)
            if (
                dn.startswith("requests.")
                and dn.count(".") == 1
                and name in _REQUESTS_VERBS
            ):
                if not _has_timeout(node, None):
                    yield self.finding(
                        ctx,
                        node,
                        f"{dn}(...) without timeout= blocks forever on a "
                        "stalled peer — pass an explicit timeout (and "
                        "consider utils/resilience.RetryPolicy + "
                        "CircuitBreaker around it).",
                    )
                continue
            if name in _TIMEOUT_POSITIONS and (
                name == dn  # bare name from a from-import
                or dn
                in (
                    f"urllib.request.{name}",
                    f"request.{name}",
                    f"http.client.{name}",
                    f"client.{name}",
                    f"socket.{name}",
                )
            ):
                if not _has_timeout(node, _TIMEOUT_POSITIONS[name]):
                    yield self.finding(
                        ctx,
                        node,
                        f"{dn or name}(...) without an explicit timeout "
                        "blocks forever on a stalled peer — pass "
                        "timeout=.",
                    )


def _walk_in_scope(root: ast.AST):
    """``ast.walk`` that does NOT descend into nested scopes (function /
    lambda / class definitions): a sleep inside a ``def`` that merely
    happens to be *defined* within a loop is not part of the loop's
    retry schedule."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                 ast.ClassDef),
            ):
                continue
            stack.append(child)


def _const_number(node: ast.AST, ctx: FileContext) -> bool:
    """Is ``node`` a compile-time numeric constant (literal, module-level
    int constant, or unary minus of one)?"""
    if isinstance(node, ast.Constant) and isinstance(
        node.value, (int, float)
    ) and not isinstance(node.value, bool):
        return True
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return _const_number(node.operand, ctx)
    return ctx.const_int(node) is not None


class BareSleepRetry(Rule):
    """A retry loop sleeping a constant (``except: time.sleep(N)`` inside
    a loop) has no jitter: every client that hit the same failure wakes
    at the same instant and stampedes the recovering dependency."""

    id = "robust-bare-sleep-retry"
    severity = "error"
    short = (
        "retry loop sleeping a constant inside an except handler "
        "(no jitter)"
    )
    motivation = (
        "constant-delay retries synchronize a fleet into thundering "
        "herds; utils/resilience.RetryPolicy gives the full-jitter "
        "schedule for free (and topology.py's lockfile retry shows the "
        "pattern)"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        seen = set()  # nested loops share handlers: report each once
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for handler in _walk_in_scope(loop):
                if (
                    not isinstance(handler, ast.ExceptHandler)
                    or id(handler) in seen
                ):
                    continue
                seen.add(id(handler))
                yield from self._sleeps_in(handler, ctx)

    def _sleeps_in(
        self, handler: ast.ExceptHandler, ctx: FileContext
    ) -> Iterator[Finding]:
        for node in _walk_in_scope(handler):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            if dn not in ("time.sleep", "sleep"):
                continue
            if node.args and _const_number(node.args[0], ctx):
                yield self.finding(
                    ctx,
                    node,
                    f"retry loop sleeps a constant ({dn}(...) in an "
                    "except handler): no jitter means synchronized "
                    "retry stampedes — use "
                    "utils/resilience.RetryPolicy's full-jitter "
                    "backoff.",
                )


def _scopes(tree: ast.AST):
    """Module + every function body as separate analysis scopes (a rename
    and its fsync belong together only when they share a scope)."""
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


class RenameNoFsync(Rule):
    """``os.replace``/``os.rename`` in a scope that never fsyncs: on
    many filesystems the rename's metadata can journal before the data
    blocks of the just-written file, so a power loss leaves the final
    name pointing at truncated or empty bytes."""

    id = "robust-rename-no-fsync"
    severity = "error"
    short = (
        "os.replace/os.rename without an fsync in the same scope "
        "(torn data under a durable name after power loss)"
    )
    motivation = (
        "LocalFSModelStore.insert shipped exactly this bug (fixed in "
        "ISSUE 3, proven by testing/crashsim.py): a crashed model PUT "
        "could leave a torn blob under the final model name; "
        "utils/durability.atomic_write_bytes packages the safe sequence"
    )

    _RENAMES = ("os.replace", "os.rename")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for scope in _scopes(ctx.tree):
            renames = []
            has_fsync = False
            for node in _walk_in_scope(scope):
                if not isinstance(node, ast.Call):
                    continue
                dn = dotted_name(node.func)
                name = call_name(node)
                if dn in self._RENAMES or (
                    name in ("replace", "rename") and dn == name
                ):
                    renames.append((node, dn or name))
                # any call whose name mentions fsync satisfies the rule:
                # os.fsync, os.fdatasync, and durability helpers like
                # fsync_file/fsync_dir/_fsync_dir all count
                if "fsync" in (name or "") or "fsync" in dn:
                    has_fsync = True
            if has_fsync:
                continue
            for node, shown in renames:
                yield self.finding(
                    ctx,
                    node,
                    f"{shown}(...) with no fsync in scope: the renamed "
                    "file's data may not be durable when the rename is — "
                    "fsync the temp file (and the directory) first, or "
                    "use utils/durability.atomic_write_bytes.",
                )


#: a function whose name carries one of these is a persistence point:
#: its writes are state some later process will trust after a crash
_CKPT_SCOPE_MARKERS = ("checkpoint", "ckpt", "snapshot", "save", "persist")

#: bare/dotted call names that write a file straight to its final path
#: (numpy's save/savez take the destination directly; json/pickle dump
#: write through a handle the same scope's open() produced)
_DIRECT_WRITE_NAMES = frozenset({"save", "savez", "savez_compressed", "dump"})


def _open_write_mode(node: ast.Call) -> bool:
    """open(...) whose mode argument creates/truncates (w/a/x). Default
    mode is read, so an open without a mode is not write evidence."""
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    return (
        isinstance(mode, ast.Constant)
        and isinstance(mode.value, str)
        and any(ch in mode.value for ch in "wax")
    )


class NonatomicCheckpoint(Rule):
    """A checkpoint/save/persist-marked function writing files with no
    atomicity evidence in scope: a crash mid-write leaves a torn file
    under the final name, and the next run — whose whole reason for the
    checkpoint is surviving exactly that crash — loads it as valid
    state. Clean shapes: any ``atomic_*`` durability helper, or the
    manual tmp-write → fsync → rename sequence in the same scope."""

    id = "robust-nonatomic-checkpoint"
    severity = "error"
    short = (
        "checkpoint/save-marked scope writes files without atomic "
        "commit evidence (torn state under the real name after a crash)"
    )
    motivation = (
        "the checkpoint subsystem (ISSUE 20) exists so a preemption "
        "costs minutes, not the run — but only if a kill mid-save can "
        "never produce a loadable half-checkpoint; ckpt/store.py's "
        "atomic_write_bytes per file + manifest-written-last is the "
        "packaged shape, and the preemption drill in bench.py proves it"
    )

    _RENAMES = ("os.replace", "os.rename")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for scope in _scopes(ctx.tree):
            if not isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            lowered = scope.name.lower()
            if not any(m in lowered for m in _CKPT_SCOPE_MARKERS):
                continue
            writes = []
            has_atomic = False
            has_rename = False
            has_fsync = False
            for node in _walk_in_scope(scope):
                if not isinstance(node, ast.Call):
                    continue
                dn = dotted_name(node.func)
                name = call_name(node) or ""
                if "atomic" in name or "atomic" in dn:
                    has_atomic = True
                if dn in self._RENAMES or (
                    name in ("replace", "rename") and dn == name
                ):
                    has_rename = True
                if "fsync" in name or "fsync" in dn:
                    has_fsync = True
                if name == "open" and dn == "open" and _open_write_mode(node):
                    writes.append((node, "open(..., 'w')"))
                elif name in _DIRECT_WRITE_NAMES:
                    writes.append((node, f"{dn or name}(...)"))
            if not writes or has_atomic or (has_rename and has_fsync):
                continue
            for node, shown in writes:
                yield self.finding(
                    ctx,
                    node,
                    f"{shown} in checkpoint-marked scope "
                    f"'{scope.name}' with no atomic-commit evidence: a "
                    "crash mid-write leaves a torn file the next run "
                    "loads as valid state — use "
                    "utils/durability.atomic_write_bytes (or tmp + "
                    "fsync + rename in this scope).",
                )


#: substrings that mark a call as introducing delay/bounding between
#: attempts: sleeps, condition waits, RetryPolicy-style schedules
_BACKOFF_MARKERS = ("sleep", "wait", "backoff", "delay")


def _truthy_const(test: ast.AST) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value)


class UnboundedRetry(Rule):
    """A ``while True`` loop re-invoking a failed call — the except
    handler swallows and the loop re-iterates — with **no attempt cap,
    no conditional exit, and no backoff**: against a dead dependency it
    retries forever at full speed. The loop never converges, never
    sheds, and stampedes the peer the moment it recovers."""

    id = "robust-unbounded-retry"
    severity = "error"
    short = (
        "while-True retry loop with no attempt cap or deadline check "
        "and no backoff between attempts"
    )
    motivation = (
        "the partitioned write path (docs/storage.md#partitioning) "
        "sheds a dead partition after a BOUNDED jittered schedule "
        "(utils/resilience.RetryPolicy); an unbounded bare retry loop "
        "instead pins a thread forever and turns the dependency's "
        "recovery into a thundering herd"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # cheap bail: the shape needs both a while loop and a handler
        if "while" not in ctx.source or "except" not in ctx.source:
            return
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, ast.While):
                continue
            if not _truthy_const(loop.test):
                continue  # a real condition IS the cap/deadline check
            handlers = [
                node for node in _walk_in_scope(loop)
                if isinstance(node, ast.ExceptHandler)
            ]
            swallowing = [
                h for h in handlers if not self._handler_exits(h)
            ]
            if not swallowing:
                continue  # every handler re-raises/returns/breaks
            if self._has_guarded_exit(loop) or self._has_backoff(loop):
                continue
            yield self.finding(
                ctx,
                loop,
                "while-True retry loop: the except handler swallows and "
                "re-iterates with no attempt cap, no conditional exit "
                "and no backoff — a dead dependency spins this thread "
                "forever; use utils/resilience.RetryPolicy (bounded "
                "attempts, full-jitter delays, deadline-aware) or bound "
                "the loop.",
            )

    @staticmethod
    def _handler_exits(handler: ast.ExceptHandler) -> bool:
        """Does the handler leave the loop (raise / return / break)?"""
        return any(
            isinstance(node, (ast.Raise, ast.Return, ast.Break))
            for node in _walk_in_scope(handler)
        )

    @staticmethod
    def _has_guarded_exit(loop: ast.While) -> bool:
        """A conditional exit anywhere in the loop — ``if attempts > N:
        raise``, ``if deadline.expired: break``, ``if done: return`` —
        bounds the retry; the *unconditional* success-path return does
        not (it is never reached while the call keeps failing)."""
        for node in _walk_in_scope(loop):
            if isinstance(node, ast.If):
                if any(
                    isinstance(sub, (ast.Raise, ast.Return, ast.Break))
                    for sub in _walk_in_scope(node)
                ):
                    return True
        return False

    @staticmethod
    def _has_backoff(loop: ast.While) -> bool:
        for node in _walk_in_scope(loop):
            if not isinstance(node, ast.Call):
                continue
            name = (call_name(node) or "").lower()
            dn = dotted_name(node.func).lower()
            if any(
                marker in name or marker in dn
                for marker in _BACKOFF_MARKERS
            ):
                return True
        return False


#: constructor shapes that mint a plain mapping (the cache container
#: candidates); lru_cache / cachetools-style bounded stores never match
_DICT_CTORS = frozenset(
    {"dict", "OrderedDict", "collections.OrderedDict"}
)

#: method calls on the container that evidence an eviction bound
_EVICTION_METHODS = frozenset({"pop", "popitem", "clear"})


def _is_dict_ctor(node: ast.AST) -> bool:
    if isinstance(node, ast.Dict):
        return True
    if isinstance(node, ast.Call) and not node.args and not node.keywords:
        return dotted_name(node.func) in _DICT_CTORS or call_name(
            node
        ) == "OrderedDict"
    return False


def _const_key(node: ast.AST) -> bool:
    """A compile-time-constant subscript key: a store under it cannot
    grow with traffic, so it is configuration, not a cache line."""
    return isinstance(node, ast.Constant)


class UnboundedCache(Rule):
    """A dict/OrderedDict named like a cache, fed by the get-then-set
    idiom on request-derived (non-constant) keys, with **no eviction
    bound anywhere in scope**: every distinct key ever seen stays
    resident. On a long-lived serving process that is a slow OOM with a
    client-controlled growth rate — the exact failure the router tier's
    response cache exists to package correctly (``fleet/cache.py``:
    LRU bound + TTL + epoch invalidation)."""

    id = "robust-unbounded-cache"
    severity = "error"
    short = (
        "dict used as a cache (get-then-set on non-constant keys) "
        "with no eviction bound in scope"
    )
    motivation = (
        "a cache keyed by request-derived values and never evicted "
        "grows with traffic until the process dies; fleet/cache.py's "
        "ResponseCache (bounded LRU + TTL + epoch invalidation) is the "
        "packaged fix — or bound the table with popitem/pop/clear/del "
        "under a size check"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # cheap bail: the rule only reasons about containers the author
        # already CALLS a cache — naming is the intent signal that keeps
        # ordinary dicts (indexes, configs, registries) out of scope
        if "cache" not in ctx.source.lower():
            return
        for name, scope in self._cache_containers(ctx):
            stores: List[ast.AST] = []
            has_read = False
            has_bound = False
            for node in ast.walk(scope):
                if self._is_store(node, name):
                    stores.append(node)
                elif self._is_read(node, name):
                    has_read = True
                if self._is_bound(node, name):
                    has_bound = True
            if has_bound or not has_read:
                continue
            for store in stores:
                yield self.finding(
                    ctx,
                    store,
                    f"{name} is written get-then-set on request-derived "
                    "keys with no eviction in scope: every distinct key "
                    "stays resident forever — bound it (LRU popitem / "
                    "TTL sweep / len() check + pop) or use "
                    "fleet/cache.py's ResponseCache.",
                )

    # -- candidate discovery ----------------------------------------------
    def _cache_containers(self, ctx: FileContext):
        """(dotted target name, analysis scope) for every empty-mapping
        assignment whose target name contains "cache". ``self.x``
        candidates analyze over the enclosing class (every method sees
        the attribute); locals over their function; globals over the
        whole module."""
        out = []

        def visit(node: ast.AST, chain: List[ast.AST]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.Assign, ast.AnnAssign)):
                    targets = (
                        child.targets
                        if isinstance(child, ast.Assign)
                        else [child.target]
                    )
                    value = child.value
                    if value is not None and _is_dict_ctor(value):
                        for target in targets:
                            name = dotted_name(target)
                            if "cache" not in name.lower():
                                continue
                            out.append((name, self._scope_for(name, chain)))
                new_chain = chain
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    new_chain = chain + [child]
                visit(child, new_chain)

        visit(ctx.tree, [ctx.tree])
        return out

    @staticmethod
    def _scope_for(name: str, chain: List[ast.AST]) -> ast.AST:
        if name.startswith("self."):
            for node in reversed(chain):
                if isinstance(node, ast.ClassDef):
                    return node
        for node in reversed(chain):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return node
        return chain[0]

    # -- evidence ----------------------------------------------------------
    @staticmethod
    def _is_store(node: ast.AST, name: str) -> bool:
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if (
                    isinstance(target, ast.Subscript)
                    and dotted_name(target.value) == name
                    and not _const_key(target.slice)
                ):
                    return True
            return False
        if isinstance(node, ast.Call):
            func = node.func
            return (
                isinstance(func, ast.Attribute)
                and func.attr == "setdefault"
                and dotted_name(func.value) == name
                and bool(node.args)
                and not _const_key(node.args[0])
            )
        return False

    @staticmethod
    def _is_read(node: ast.AST, name: str) -> bool:
        if (
            isinstance(node, ast.Subscript)
            and isinstance(node.ctx, ast.Load)
            and dotted_name(node.value) == name
        ):
            return True
        if isinstance(node, ast.Call):
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in ("get", "setdefault")
                and dotted_name(func.value) == name
            ):
                return True
        if isinstance(node, ast.Compare):
            return any(
                isinstance(op, (ast.In, ast.NotIn))
                and dotted_name(comp) == name
                for op, comp in zip(node.ops, node.comparators)
            )
        return False

    @staticmethod
    def _is_bound(node: ast.AST, name: str) -> bool:
        """Eviction evidence: pop/popitem/clear on the container, a
        ``del container[...]``, or a ``len(container)`` read (the size
        check an eviction loop hangs off — present exactly when someone
        thought about the bound)."""
        if isinstance(node, ast.Call):
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in _EVICTION_METHODS
                and dotted_name(func.value) == name
            ):
                return True
            if (
                call_name(node) == "len"
                and len(node.args) == 1
                and dotted_name(node.args[0]) == name
            ):
                return True
        if isinstance(node, ast.Delete):
            return any(
                isinstance(t, ast.Subscript)
                and dotted_name(t.value) == name
                for t in node.targets
            )
        return False


_FLIP_MARKERS = ("cutover", "flip", "switch", "swap", "promote", "migrat")
_BARRIER_MARKERS = (
    "watermark", "drain", "barrier", "flush", "quiesce", "catch",
    "verify", "freeze", "wait", "join", "sync",
)


def _dotted_source(node: ast.AST) -> str:
    """A plain dotted read (``self._new``, ``new_layout``) — the shape a
    store/layout handle has at a flip site.  Returns ``""`` for anything
    computed (calls, subscripts), which never counts as a flip source."""
    if isinstance(node, (ast.Name, ast.Attribute)):
        return dotted_name(node) or ""
    return ""


class CutoverNoWatermark(Rule):
    """A cutover-named function that flips a read/write path between two
    stores/layouts — the same dotted target assigned one source per
    branch — with no drain/watermark/barrier evidence anywhere in the
    function.  Flipping without verifying the lagging side caught up
    strands every in-flight write on a path nothing reads anymore: the
    acks were real, the data is gone from the reader's universe."""

    id = "robust-cutover-no-watermark"
    severity = "error"
    short = (
        "cutover flips between two stores/layouts with no "
        "watermark/drain evidence in scope"
    )
    motivation = (
        "a layout flip is only safe behind a verified barrier (drain "
        "the mirror queue, check the backfill watermark, freeze "
        "writers); storage/migration.py's cutover() — freeze, final "
        "drain, per-keyspace watermark, then the flip — is the "
        "packaged shape"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        lowered = ctx.source.lower()
        if not any(m in lowered for m in _FLIP_MARKERS):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            lname = node.name.lower()
            if not any(m in lname for m in _FLIP_MARKERS):
                continue
            sites = self._flip_sites(node)
            if not sites or self._has_barrier(node):
                continue
            for site in sites:
                yield self.finding(
                    ctx,
                    site,
                    f"{node.name}() flips between two stores/layouts "
                    "with no watermark/drain/barrier evidence in "
                    "scope — verify the lagging side caught up "
                    "(drain the queue, check the watermark) before "
                    "the flip, or every in-flight write is stranded "
                    "on the retired path.",
                )

    # -- flip-site detection ------------------------------------------

    @classmethod
    def _flip_sites(cls, fn: ast.AST) -> List[ast.AST]:
        sites: List[ast.AST] = []
        for node in _walk_in_scope(fn):
            if isinstance(node, ast.If) and node.orelse:
                body = cls._branch_assigns(node.body)
                orelse = cls._branch_assigns(node.orelse)
                for target, src_a in body.items():
                    src_b = orelse.get(target)
                    if src_b is None:
                        continue
                    if cls._two_sources(src_a, src_b):
                        sites.append(node)
                        break
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.IfExp
            ):
                if (
                    len(node.targets) == 1
                    and _dotted_source(node.targets[0])
                    and cls._two_sources(
                        node.value.body, node.value.orelse
                    )
                ):
                    sites.append(node)
        return sites

    @staticmethod
    def _branch_assigns(stmts) -> dict:
        """Map of dotted-target -> source node for the plain
        handle-from-handle assignments in one branch of an ``if``."""
        out: dict = {}
        for stmt in stmts:
            for node in _walk_in_scope(stmt):
                if not isinstance(node, ast.Assign):
                    continue
                if len(node.targets) != 1:
                    continue
                target = _dotted_source(node.targets[0])
                if target and _dotted_source(node.value):
                    out[target] = node.value
        return out

    @staticmethod
    def _two_sources(a: ast.AST, b: ast.AST) -> bool:
        """Two *different* same-shaped dotted sources — the signature of
        choosing between two live handles rather than resetting one."""
        da, db = _dotted_source(a), _dotted_source(b)
        return bool(da) and bool(db) and da != db and type(a) is type(b)

    @staticmethod
    def _has_barrier(fn: ast.AST) -> bool:
        """Barrier evidence: any identifier in the function's own scope
        (not nested defs) that names a drain/watermark/freeze step."""
        for node in _walk_in_scope(fn):
            if isinstance(node, ast.Name):
                ident = node.id.lower()
            elif isinstance(node, ast.Attribute):
                ident = node.attr.lower()
            else:
                continue
            if any(m in ident for m in _BARRIER_MARKERS):
                return True
        return False


#: identifiers that mark an except handler as a *deliberate* degrade
#: path — the rule's gate: only code that advertises "I fall back" is
#: held to the recording contract (an ordinary except is rules_obs's
#: business, not this rule's)
_FALLBACK_MARKERS = ("fallback", "fall_back", "degrade", "advisory")

#: dotted-name components that count as recording the failure —
#: loggers, metric counters, flight recorders; substring match per
#: component, benefit of the doubt on purpose (a false "recorded" is
#: cheaper than training people to ignore the rule)
_RECORD_MARKERS = (
    "log", "warn", "error", "exception", "debug", "info", "inc",
    "observe", "record", "count", "note", "emit", "flight", "metric",
)


class FallbackSwallows(Rule):
    """A fallback/degrade-marked except handler that discards the
    primary failure without recording it. The degrade path *working* is
    exactly what makes the swallow dangerous: clients see answers, no
    error rate moves, and the primary stays dead until the day the
    fallback also fails — at which point the incident starts with zero
    history. A degrade is only safe when every occurrence leaves a
    trace (``fleet/sharedcache.py``'s ``_record_degrade``: count the
    outcome, keep ``last_error``, debug-log, THEN return the miss)."""

    id = "robust-fallback-swallows"
    severity = "error"
    short = (
        "fallback/degrade except handler discards the primary "
        "failure without recording it"
    )
    motivation = (
        "a silent degrade path turns a dead primary into a latent "
        "incident with no history; record every occurrence (counter, "
        "log, last_error) before returning the fallback answer — "
        "fleet/sharedcache.py's _record_degrade is the packaged shape"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        lowered = ctx.source.lower()
        if not any(m in lowered for m in _FALLBACK_MARKERS):
            return
        for handler, fn_name in self._handlers(ctx.tree):
            if not self._gated(handler, fn_name):
                continue
            if self._records(handler):
                continue
            yield self.finding(
                ctx,
                handler,
                (
                    f"{fn_name}(): " if fn_name else ""
                )
                + "this fallback/degrade handler swallows the primary "
                "failure — nothing logs, counts, or keeps the "
                "exception, so the degrade is invisible until the "
                "fallback ALSO fails. Record the failure (counter + "
                "last_error + log, fleet/sharedcache.py's "
                "_record_degrade shape) before returning the "
                "fallback answer.",
            )

    @staticmethod
    def _handlers(tree: ast.AST):
        """Every except handler, paired with its enclosing function's
        name ("" at module level) — the gate looks at both."""
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn_name = node.name
                for sub in _walk_in_scope(node):
                    if isinstance(sub, ast.Try):
                        for handler in sub.handlers:
                            yield handler, fn_name
        # module-level try blocks (import fallbacks and the like)
        for node in ast.iter_child_nodes(tree):
            if isinstance(node, ast.Try):
                for handler in node.handlers:
                    yield handler, ""

    @classmethod
    def _gated(cls, handler: ast.ExceptHandler, fn_name: str) -> bool:
        """In scope iff the code ADVERTISES a degrade: the enclosing
        function's name or any identifier inside the handler carries a
        fallback marker."""
        lname = fn_name.lower()
        if any(m in lname for m in _FALLBACK_MARKERS):
            return True
        for ident in cls._handler_idents(handler):
            if any(m in ident for m in _FALLBACK_MARKERS):
                return True
        return False

    @staticmethod
    def _handler_idents(handler: ast.ExceptHandler):
        for node in _walk_in_scope(handler):
            if isinstance(node, ast.Name):
                yield node.id.lower()
            elif isinstance(node, ast.Attribute):
                yield node.attr.lower()

    @staticmethod
    def _records(handler: ast.ExceptHandler) -> bool:
        """Recording evidence inside the handler: a re-raise, a call
        whose dotted name carries a logger/counter component, an
        assignment to an error-named slot, or ANY read of the bound
        exception (an exception that flows somewhere was not
        discarded)."""
        bound = handler.name
        for node in _walk_in_scope(handler):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                name = dotted_name(node.func) or call_name(node)
                for part in name.lower().split("."):
                    if any(m in part for m in _RECORD_MARKERS):
                        return True
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    tname = (
                        target.attr
                        if isinstance(target, ast.Attribute)
                        else target.id
                        if isinstance(target, ast.Name)
                        else ""
                    ).lower()
                    if "error" in tname or "fail" in tname:
                        return True
            if (
                bound
                and isinstance(node, ast.Name)
                and node.id == bound
                and isinstance(node.ctx, ast.Load)
            ):
                return True
        return False


RULES: List[Rule] = [
    NoTimeout(), BareSleepRetry(), RenameNoFsync(), NonatomicCheckpoint(),
    UnboundedRetry(), UnboundedCache(), CutoverNoWatermark(),
    FallbackSwallows(),
]
