"""Family D — observability hygiene rules, applied package-wide.

The metrics plane (``predictionio_tpu/obs``, ISSUE 4) bounds label
cardinality at runtime (over-cap label sets collapse into
``{label="_overflow"}``), but the *bug* — a label value interpolated
from unbounded request data (user ids, event ids, raw paths, query
strings) — is mechanical and visible at AST level, so it is caught
before it ships, like the Mosaic and robustness families:

- ``obs-unbounded-label``: a keyword argument to a metric observation
  (``inc``/``dec``/``set``/``observe``/``labels``, or the values of a
  ``gauge_callback(labels={...})`` literal) built by string
  interpolation — f-string, ``.format``, ``%``, concatenation, or
  ``str(...)`` — is almost always a per-request value. Every distinct
  value is a new time series the scraper stores forever; interpolation
  is how unbounded sets get in. Use a closed vocabulary (route
  templates, outcome kinds, dependency names) and put the variable part
  in a *span tag* (ring-buffered, not a time series) instead.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from .engine import FileContext, Finding, Rule

#: metric-observation methods whose keyword arguments are label values
_OBS_METHODS = frozenset({"inc", "dec", "set", "observe", "labels"})

#: keyword names on those methods that are NOT labels
_NON_LABEL_KWARGS = frozenset({"amount", "value"})


def _is_interpolated(node: ast.AST) -> bool:
    """Is ``node`` a string built at runtime from embedded values?"""
    if isinstance(node, ast.JoinedStr):
        # an f-string with at least one substitution (a plain f"text"
        # with no braces is just a constant)
        return any(
            isinstance(part, ast.FormattedValue) for part in node.values
        )
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "format":
            return True
        if isinstance(fn, ast.Name) and fn.id == "str" and node.args:
            return True
        return False
    if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.Add, ast.Mod)):
        # "prefix-" + x  /  "user-%s" % x: interpolation when either side
        # is (or contains) a string literal
        return _has_str_constant(node.left) or _has_str_constant(node.right)
    return False


def _has_str_constant(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            return True
        if isinstance(sub, ast.JoinedStr):
            return True
    return False


class UnboundedLabel(Rule):
    """A metric label value assembled by string interpolation: every
    distinct value is a permanent new time series — request-derived
    values blow the cardinality bound and land in ``_overflow``, taking
    the signal with them."""

    id = "obs-unbounded-label"
    severity = "error"
    short = (
        "metric label value interpolated from runtime data (f-string/"
        "format/%/concat/str()) — unbounded cardinality"
    )
    motivation = (
        "a label value is a time series key the scraper stores forever; "
        "obs/metrics.py caps a metric's label sets and folds the excess "
        "into {label=\"_overflow\"}, so an interpolated request value "
        "doesn't just leak memory — it silently destroys the metric. "
        "Label with a closed vocabulary (route template, outcome kind, "
        "dependency name); put per-request detail in span tags."
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not isinstance(fn, ast.Attribute):
                continue
            if fn.attr in _OBS_METHODS:
                for kw in node.keywords:
                    if kw.arg is None or kw.arg in _NON_LABEL_KWARGS:
                        continue
                    if _is_interpolated(kw.value):
                        yield self.finding(
                            ctx,
                            kw.value,
                            f"label {kw.arg!r} is interpolated from "
                            "runtime data: each distinct value is a new "
                            "time series — use a closed label "
                            "vocabulary and put the variable part in a "
                            "span tag.",
                        )
            elif fn.attr == "gauge_callback":
                labels = next(
                    (kw.value for kw in node.keywords if kw.arg == "labels"),
                    None,
                )
                if isinstance(labels, ast.Dict):
                    for value in labels.values:
                        if value is not None and _is_interpolated(value):
                            yield self.finding(
                                ctx,
                                value,
                                "gauge_callback label value is "
                                "interpolated from runtime data — use a "
                                "closed label vocabulary.",
                            )


RULES: List[Rule] = [UnboundedLabel()]
