"""Family D — observability hygiene rules, applied package-wide.

The metrics plane (``predictionio_tpu/obs``, ISSUE 4) bounds label
cardinality at runtime (over-cap label sets collapse into
``{label="_overflow"}``), but the *bug* — a label value interpolated
from unbounded request data (user ids, event ids, raw paths, query
strings) — is mechanical and visible at AST level, so it is caught
before it ships, like the Mosaic and robustness families:

- ``obs-unbounded-label``: a keyword argument to a metric observation
  (``inc``/``dec``/``set``/``observe``/``labels``, or the values of a
  ``gauge_callback(labels={...})`` literal) built by string
  interpolation — f-string, ``.format``, ``%``, concatenation, or
  ``str(...)`` — is almost always a per-request value. Every distinct
  value is a new time series the scraper stores forever; interpolation
  is how unbounded sets get in. Use a closed vocabulary (route
  templates, outcome kinds, dependency names) and put the variable part
  in a *span tag* (ring-buffered, not a time series) instead.
- ``perf-unfenced-timing`` (ISSUE 8): ``time.monotonic()`` /
  ``time.perf_counter()`` bracketing a call to a jitted function with
  no ``block_until_ready`` (or another forcing call) before the stop
  read. JAX dispatch is asynchronous — the stop fires when the call
  *returned*, not when the device finished, so the "measurement" is the
  dispatch overhead plus whatever the runtime happened to overlap. The
  number then drives real decisions (BENCH records, lever A/Bs) while
  measuring nothing. Where dispatch time IS the intended measurement,
  suppress with a reason.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .engine import FileContext, Finding, Rule, call_name, dotted_name

#: metric-observation methods whose keyword arguments are label values
_OBS_METHODS = frozenset({"inc", "dec", "set", "observe", "labels"})

#: keyword names on those methods that are NOT labels
_NON_LABEL_KWARGS = frozenset({"amount", "value"})


def _is_interpolated(node: ast.AST) -> bool:
    """Is ``node`` a string built at runtime from embedded values?"""
    if isinstance(node, ast.JoinedStr):
        # an f-string with at least one substitution (a plain f"text"
        # with no braces is just a constant)
        return any(
            isinstance(part, ast.FormattedValue) for part in node.values
        )
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "format":
            return True
        if isinstance(fn, ast.Name) and fn.id == "str" and node.args:
            return True
        return False
    if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.Add, ast.Mod)):
        # "prefix-" + x  /  "user-%s" % x: interpolation when either side
        # is (or contains) a string literal
        return _has_str_constant(node.left) or _has_str_constant(node.right)
    return False


def _has_str_constant(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            return True
        if isinstance(sub, ast.JoinedStr):
            return True
    return False


class UnboundedLabel(Rule):
    """A metric label value assembled by string interpolation: every
    distinct value is a permanent new time series — request-derived
    values blow the cardinality bound and land in ``_overflow``, taking
    the signal with them."""

    id = "obs-unbounded-label"
    severity = "error"
    short = (
        "metric label value interpolated from runtime data (f-string/"
        "format/%/concat/str()) — unbounded cardinality"
    )
    motivation = (
        "a label value is a time series key the scraper stores forever; "
        "obs/metrics.py caps a metric's label sets and folds the excess "
        "into {label=\"_overflow\"}, so an interpolated request value "
        "doesn't just leak memory — it silently destroys the metric. "
        "Label with a closed vocabulary (route template, outcome kind, "
        "dependency name); put per-request detail in span tags."
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not isinstance(fn, ast.Attribute):
                continue
            if fn.attr in _OBS_METHODS:
                for kw in node.keywords:
                    if kw.arg is None or kw.arg in _NON_LABEL_KWARGS:
                        continue
                    if _is_interpolated(kw.value):
                        yield self.finding(
                            ctx,
                            kw.value,
                            f"label {kw.arg!r} is interpolated from "
                            "runtime data: each distinct value is a new "
                            "time series — use a closed label "
                            "vocabulary and put the variable part in a "
                            "span tag.",
                        )
            elif fn.attr == "gauge_callback":
                labels = next(
                    (kw.value for kw in node.keywords if kw.arg == "labels"),
                    None,
                )
                if isinstance(labels, ast.Dict):
                    for value in labels.values:
                        if value is not None and _is_interpolated(value):
                            yield self.finding(
                                ctx,
                                value,
                                "gauge_callback label value is "
                                "interpolated from runtime data — use a "
                                "closed label vocabulary.",
                            )


# -- perf-unfenced-timing ---------------------------------------------------

#: a timing-read call: time.monotonic() / time.perf_counter(), however
#: the module was imported (``import time as _time`` is common here)
_CLOCK_TAILS = ("monotonic", "perf_counter")

#: calls that force device completion (or materialize to host) before
#: the stop read — any of these between the last jitted call and the
#: stop makes the measurement honest
_FENCE_CALL_NAMES = frozenset(
    {"block_until_ready", "device_get", "asarray", "item"}
)


def _is_clock_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    dn = dotted_name(node.func)
    return dn in _CLOCK_TAILS or any(
        dn.endswith("." + tail) for tail in _CLOCK_TAILS
    )


def _is_fence_call(node: ast.Call) -> bool:
    name = call_name(node)
    return name in _FENCE_CALL_NAMES


def _walk_same_scope(root: ast.AST):
    """``ast.walk`` that does not descend into nested function/class
    scopes: a jitted call inside a nested ``def`` is not executed
    between this scope's start and stop reads."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef),
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _jit_value_kind(node: ast.AST) -> Optional[str]:
    """Classify an assignment RHS / decorator: "jit" for
    ``jax.jit(...)`` or ``functools.partial(jax.jit, ...)`` (optionally
    immediately applied), else None."""
    if not isinstance(node, ast.Call):
        return None
    dn = dotted_name(node.func)
    if dn in ("jax.jit", "jit"):
        return "jit"
    if dn in ("functools.partial", "partial") and any(
        dotted_name(arg) in ("jax.jit", "jit") for arg in node.args
    ):
        return "jit"
    # partial(jax.jit, ...)(body) / jax.jit(...)(body)-style application
    if isinstance(node.func, ast.Call) and _jit_value_kind(node.func):
        return "jit"
    return None


def _scope_assigns(scope: ast.AST) -> List[Tuple[str, ast.AST]]:
    """Single-Name-target assignments lexically in ``scope`` (nested
    function/class bodies excluded — their locals are not this scope's)."""
    out: List[Tuple[str, ast.AST]] = []
    for node in _walk_same_scope(scope):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            if isinstance(target, ast.Name):
                out.append((target.id, node.value))
    return out


def _resolve_jitted(
    assigns: List[Tuple[str, ast.AST]],
    base: Set[str],
    factories: Set[str],
) -> Set[str]:
    """Settle which names ``assigns`` leave bound to jitted callables,
    starting from ``base`` (enclosing-scope jitted names). A non-jit
    assignment SHADOWS: ``f = make_reader()`` in a function must erase a
    module-level jitted ``f`` for that function's scope — timing the
    local is honest host timing, not an unfenced dispatch."""
    jitted = set(base)
    # two passes settle alias-of-alias and factory-result chains without
    # order sensitivity (module constants often precede their use)
    for _ in range(2):
        for name, value in assigns:
            if _jit_value_kind(value):
                jitted.add(name)
            elif isinstance(value, ast.Name) and value.id in jitted:
                jitted.add(name)
            elif (
                isinstance(value, ast.Call)
                and dotted_name(value.func) in factories
            ):
                jitted.add(name)
            else:
                jitted.discard(name)
    return jitted


def _collect_module_jitted(tree: ast.AST) -> Tuple[Set[str], Set[str]]:
    """Module-level jitted names + jit-returning factory names: direct
    ``jax.jit`` results, decorated defs, results of factory functions
    that ``return jax.jit(...)``, and one-hop aliases of any of those."""
    jitted: Set[str] = set()
    factories: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_jit_value_kind(dec) for dec in node.decorator_list):
                jitted.add(node.name)
            for sub in ast.walk(node):
                if isinstance(sub, ast.Return) and _jit_value_kind(
                    sub.value
                ):
                    factories.add(node.name)
                    break
    jitted = _resolve_jitted(_scope_assigns(tree), jitted, factories)
    return jitted, factories


def _is_jitted_call(node: ast.Call, jitted: Set[str]) -> bool:
    dn = dotted_name(node.func)
    if dn in jitted or call_name(node) in jitted:
        return True
    # calls routed through a wrapper (JitTelemetry.call(name, fn, ...))
    # still dispatch the jitted positional argument
    return any(
        isinstance(arg, ast.Name) and arg.id in jitted
        for arg in node.args
    )


class UnfencedTiming(Rule):
    """A monotonic/perf_counter bracket around a jitted call with no
    ``block_until_ready`` (or other forcing read) before the stop: jax
    dispatch is async, so the clock measures dispatch, not the device —
    the number is a lie that then drives perf decisions."""

    id = "perf-unfenced-timing"
    severity = "error"
    short = (
        "time.monotonic()/perf_counter() bracketing a jitted call with "
        "no block_until_ready before the stop (async dispatch — the "
        "measurement is a lie)"
    )
    motivation = (
        "ISSUE 8: BENCH numbers and lever A/Bs are evidence; an "
        "unfenced bracket around an async dispatch records dispatch "
        "overhead as if it were device time. ops/als.py fences every "
        "iteration timing (jax.block_until_ready) — new timing code "
        "must too, or suppress with a reason where dispatch time is "
        "the intended measurement."
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        module_jitted, factories = _collect_module_jitted(ctx.tree)
        scopes: List[ast.AST] = [ctx.tree]
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append(node)
        for scope in scopes:
            # per-scope name resolution: a function's own ``f = ...``
            # binding wins over a module-level jitted ``f`` (no cross-
            # scope pooling — an unrelated same-named host callable in
            # another function must not trip the rule)
            if scope is ctx.tree:
                jitted = module_jitted
            else:
                base = set(module_jitted)
                args = scope.args
                for arg in (
                    list(args.posonlyargs) + list(args.args)
                    + list(args.kwonlyargs)
                    + [a for a in (args.vararg, args.kwarg) if a]
                ):
                    base.discard(arg.arg)  # parameters shadow too
                jitted = _resolve_jitted(
                    _scope_assigns(scope), base, factories
                )
            if not jitted:
                continue
            yield from self._check_scope(ctx, scope, jitted)

    def _check_scope(
        self, ctx: FileContext, scope: ast.AST, jitted: Set[str]
    ) -> Iterator[Finding]:
        starts: Dict[str, List[int]] = {}
        jit_lines: List[int] = []
        fence_lines: List[int] = []
        stops: List[Tuple[int, str, ast.AST]] = []
        for node in _walk_same_scope(scope):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and _is_clock_call(node.value)
            ):
                starts.setdefault(node.targets[0].id, []).append(
                    node.lineno
                )
            elif isinstance(node, ast.Call):
                if _is_fence_call(node):
                    fence_lines.append(node.lineno)
                elif _is_jitted_call(node, jitted):
                    jit_lines.append(node.lineno)
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
                if _is_clock_call(node.left) and isinstance(
                    node.right, ast.Name
                ):
                    stops.append((node.lineno, node.right.id, node))
        for stop_line, var, stop_node in stops:
            candidates = [
                line for line in starts.get(var, ()) if line < stop_line
            ]
            if not candidates:
                continue
            start_line = max(candidates)
            in_bracket = [
                line
                for line in jit_lines
                if start_line < line <= stop_line
            ]
            if not in_bracket:
                continue
            last_jit = max(in_bracket)
            if any(
                last_jit <= line <= stop_line for line in fence_lines
            ):
                continue
            yield self.finding(
                ctx,
                stop_node,
                f"timing stop reads {var!r} after a jitted call with no "
                "block_until_ready in between: async dispatch means this "
                "measures dispatch, not device time — fence the result "
                "(jax.block_until_ready / np.asarray) before the stop, "
                "or suppress with a reason if dispatch time is the "
                "point.",
            )


# -- obs-swallowed-observer ---------------------------------------------------

#: method/name tails whose calls mark a try body as an observer path:
#: quality monitors, served-list recording, watcher taps
_OBSERVER_CALL_NAMES = frozenset(
    {
        "observe_result", "record_event", "record_rejected",
        "record_feedback", "record_scores", "record_served",
        "model_live", "on_event", "tap",
    }
)


def _is_observer_function(name: str) -> bool:
    return (
        name.startswith("_observe")
        or name.startswith("observe_")
        or name == "on_event"
        or name.endswith("_tap")
    )


def _name_tail(node: ast.expr) -> str:
    dn = dotted_name(node)
    return dn.rsplit(".", 1)[-1] if dn else ""


def _calls_observer(body: List[ast.stmt]) -> bool:
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                if _name_tail(node.func) in _OBSERVER_CALL_NAMES:
                    return True
    return False


def _accounts_failure(stmts: List[ast.stmt]) -> bool:
    """Does this block raise, or count the failure into a metric? A
    ``.inc(`` call is the canonical counter bump; a call whose name
    ends in ``_error``/``_errors`` is the hook-shaped variant
    (``on_event_error``) an object without its own registry uses.
    Deliberately NOT a substring match: ``logger.error(...)`` is
    exactly the log-only swallow this rule exists to catch."""
    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "inc"
                ):
                    return True
                tail = _name_tail(node.func).lower()
                if tail.endswith("_error") or tail.endswith("_errors"):
                    return True
    return False


class SwallowedObserver(Rule):
    """An observer/callback path (quality monitors, ``_observe_*``
    helpers, watcher taps) that swallows exceptions without
    incrementing a counter: the swallow is correct — observability must
    never fail the observed path — but an UNCOUNTED swallow makes a
    permanently broken observer indistinguishable from a healthy one."""

    id = "obs-swallowed-observer"
    severity = "error"
    short = (
        "observer/tap except-handler swallows without counting "
        "(no .inc() / raise) — a dead observer becomes invisible"
    )
    motivation = (
        "the serving/ingest planes deliberately swallow observer "
        "exceptions so a monitor fault never fails a query or drops a "
        "stored event; the cost is that a monitor broken on EVERY call "
        "(schema change, corrupt state) looks exactly like a healthy "
        "one. Counting the swallow (pio_observer_errors_total{site}, "
        "or an on_event_error hook) keeps the failure observable — "
        "accounting in the try's finally (an outcome counter) also "
        "satisfies the rule."
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # map each Try to its nearest enclosing function name
        func_stack: List[str] = []

        def visit(node: ast.AST) -> Iterator[Finding]:
            is_func = isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            )
            if is_func:
                func_stack.append(node.name)
            if isinstance(node, ast.Try):
                observerish = (
                    (func_stack and _is_observer_function(func_stack[-1]))
                    or _calls_observer(node.body)
                )
                if observerish and not _accounts_failure(node.finalbody):
                    for handler in node.handlers:
                        if not _accounts_failure(handler.body):
                            yield self.finding(
                                ctx,
                                handler,
                                "observer path swallows exceptions "
                                "without counting them: increment a "
                                "counter (pio_observer_errors_total) "
                                "or an error hook in the handler — or "
                                "suppress with a reason if the "
                                "failure is accounted elsewhere.",
                            )
            for child in ast.iter_child_nodes(node):
                yield from visit(child)
            if is_func:
                func_stack.pop()

        yield from visit(ctx.tree)


RULES: List[Rule] = [UnboundedLabel(), UnfencedTiming(), SwallowedObserver()]
