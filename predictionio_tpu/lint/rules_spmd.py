"""Family F — SPMD / multi-host consistency rules, applied package-wide.

The ROADMAP's next arc is pod-scale distributed training (ALX-style
sharded ALS): every host runs the *same* program and the collectives
only line up if the programs really are the same. The divergence bug
classes are mechanical — a collective issued under host-dependent
control flow deadlocks the pod; an ``axis_name`` that doesn't match the
enclosing mesh axes fails at trace time on hardware you only get for a
day; hash-ordered iteration feeding device placement gives every host a
different operand order — so they are caught at AST level, like the
Mosaic rules, before a pod ever runs:

- ``spmd-collective-host-branch``: a collective (``psum``,
  ``all_gather``, ...) inside an ``if jax.process_index() == 0:``-style
  branch runs on *some* hosts only; the others block in the collective
  until the heartbeat kills the job.
- ``spmd-axis-name-mismatch``: a collective's literal ``axis_name``
  must name an axis of the enclosing ``shard_map``/``pmap`` mesh;
  anything else is an unbound-axis trace error on device day.
- ``spmd-spec-rank-mismatch``: for a rank-preserving mapped body,
  ``in_specs``/``out_specs`` literals of different ranks describe an
  impossible sharding and die in shard_map's pytree/rank checks.
- ``spmd-shard-map-arity``: ``in_specs`` entries must match the mapped
  function's positional arity — a drifted spec list silently shards the
  wrong operand before it fails.
- ``spmd-unordered-collective-operand``: iterating a ``set`` to build
  device operands (``device_put``/``make_array_from_single_device_arrays``
  /collectives) is hash-order — different processes can disagree on the
  order. Sort first.
- ``spmd-host-dependent-rng``: ``PRNGKey(time/pid/urandom...)`` seeds
  diverge across hosts and runs; inside a sharded function a
  ``process_index()``-dependent seed makes the "same" program sample
  different randomness per host.
- ``spmd-collective-missing-axis``: a collective inside a
  shard_map/pmap-mapped body with no axis argument at all is a
  trace-time ``TypeError`` — but ONLY when the sharded path actually
  traces, which for mesh-gated trainers is on the hardware day, not at
  your desk.
- ``spmd-unguarded-downcast``: a cast below f32 (int8/bf16/fp8/...)
  inside a serve/train/predict-marked function with no gate-shaped
  check (``*_gate``, ``rmse``, ``topk_match*``, allclose) in the same
  scope — precision leaves the data path with nothing measuring the
  cost (docs/quantization.md#gate).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from .engine import (
    FileContext,
    Finding,
    Rule,
    call_name,
    dotted_name,
    walk_in_scope,
)

#: collective primitive → positional index of its axis-name argument
_COLLECTIVES: Dict[str, int] = {
    "psum": 1,
    "pmean": 1,
    "pmax": 1,
    "pmin": 1,
    "all_gather": 1,
    "all_to_all": 1,
    "psum_scatter": 1,
    "ppermute": 1,
    "pshuffle": 1,
    "axis_index": 0,
}

#: collectives that preserve operand rank (the spec-rank rule's scope);
#: ``all_gather`` only with ``tiled=True``
_RANK_PRESERVING = frozenset(
    {"psum", "pmean", "pmax", "pmin", "psum_scatter", "ppermute"}
)


def _is_collective(node: ast.Call) -> bool:
    name = call_name(node)
    if name not in _COLLECTIVES:
        return False
    dn = dotted_name(node.func)
    return dn in (name, f"lax.{name}", f"jax.lax.{name}")


def _collective_axis_arg(node: ast.Call) -> Optional[ast.AST]:
    for kw in node.keywords:
        if kw.arg == "axis_name":
            return kw.value
    pos = _COLLECTIVES[call_name(node)]
    if len(node.args) > pos:
        return node.args[pos]
    return None


def _is_host_divergent_call(node: ast.AST) -> bool:
    """``jax.process_index()`` / ``host_id()``-shaped calls — values that
    differ between the processes of one SPMD job."""
    if not isinstance(node, ast.Call):
        return False
    tail = dotted_name(node.func).rsplit(".", 1)[-1]
    return tail in ("process_index", "host_id")


def _scopes(tree: ast.AST):
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


class CollectiveHostBranch(Rule):
    """A collective under host-divergent control flow runs on a strict
    subset of the job's processes; the rest block in their matching
    collective (or skip it and desynchronize the program counter) until
    the coordination service kills the job — the failure mode behind
    hung pods that look healthy from every dashboard."""

    id = "spmd-collective-host-branch"
    severity = "error"
    short = (
        "collective (psum/all_gather/...) inside an "
        "`if process_index() ...` branch — some hosts never issue it"
    )
    motivation = (
        "the seed peer-death failure is exactly a pod blocking in a "
        "collective its peer never reached; host-divergent control "
        "flow writes that hang on purpose"
    )

    #: cheap source-text bail markers (no marker → no possible finding)
    _MARKERS = ("process_index", "host_id", "process_info")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not any(m in ctx.source for m in self._MARKERS) or not any(
            c in ctx.source for c in _COLLECTIVES
        ):
            return
        for scope in _scopes(ctx.tree):
            divergent_names = self._divergent_names(scope)
            for stmt in walk_in_scope(scope):
                if not isinstance(stmt, (ast.If, ast.While)):
                    continue
                if not self._test_is_divergent(stmt.test, divergent_names):
                    continue
                for sub in walk_in_scope(stmt):
                    if isinstance(sub, ast.Call) and _is_collective(sub):
                        yield self.finding(
                            ctx,
                            sub,
                            f"{dotted_name(sub.func)}(...) under "
                            "host-divergent control flow (the branch "
                            "tests process_index/host_id): hosts that "
                            "skip the branch never join the collective "
                            "and the pod hangs — issue the collective "
                            "unconditionally and branch on the result.",
                        )

    @staticmethod
    def _divergent_names(scope: ast.AST) -> Set[str]:
        """Names assigned (possibly tuple-unpacked) from a
        process_index/host_id/process_info call in this scope."""
        out: Set[str] = set()
        for node in walk_in_scope(scope):
            if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call
            ):
                continue
            tail = dotted_name(node.value.func).rsplit(".", 1)[-1]
            if tail in ("process_index", "host_id"):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
            elif tail == "process_info":
                # rank, world = process_info(): only the rank diverges
                for t in node.targets:
                    if isinstance(t, (ast.Tuple, ast.List)) and t.elts and \
                            isinstance(t.elts[0], ast.Name):
                        out.add(t.elts[0].id)
        return out

    @staticmethod
    def _test_is_divergent(test: ast.AST, names: Set[str]) -> bool:
        for node in ast.walk(test):
            if _is_host_divergent_call(node):
                return True
            if isinstance(node, ast.Name) and node.id in names:
                return True
        return False


def _resolve_mapped_fn(
    call: ast.Call, ctx: FileContext
) -> Optional[ast.AST]:
    """The function/lambda a shard_map/pmap call maps — one resolution
    semantics shared by every family-F rule (first matching def in tree
    order), so no two rules can judge different bodies for one call."""
    if not call.args:
        return None
    fn = call.args[0]
    # functools.partial(body, ...): the mapped callable IS the bound
    # function — judge its body, not the partial wrapper
    if (
        isinstance(fn, ast.Call)
        and dotted_name(fn.func) in ("partial", "functools.partial")
        and fn.args
    ):
        fn = fn.args[0]
    if isinstance(fn, ast.Lambda):
        return fn
    if isinstance(fn, ast.Name):
        for sub in ast.walk(ctx.tree):
            if isinstance(sub, ast.FunctionDef) and sub.name == fn.id:
                return sub
    return None


def _mapped_functions(ctx: FileContext) -> List[ast.AST]:
    """Function/lambda nodes passed as the mapped body to shard_map or
    pmap anywhere in the file."""
    out: List[ast.AST] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and call_name(node) in (
            "shard_map", "pmap"
        ):
            fn = _resolve_mapped_fn(node, ctx)
            if fn is not None:
                out.append(fn)
    return out


def _kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _mesh_literal_axes(
    node: ast.AST, scope: ast.AST, _depth: int = 0
) -> Set[str]:
    """Axis names from a ``Mesh(devices, ("a", "b"))`` literal — the
    node itself, or one ``Name`` hop to its assignment in the SAME
    scope (cross-scope lookups would collide on common names like
    ``mesh``). Empty when not statically resolvable, or when the scope
    assigns the name two different literal axis sets (ambiguous)."""
    if isinstance(node, ast.Call) and call_name(node) == "Mesh":
        candidates = list(node.args[1:2]) + [
            kw.value for kw in node.keywords if kw.arg == "axis_names"
        ]
        for arg in candidates:
            if isinstance(arg, (ast.Tuple, ast.List)) and arg.elts and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in arg.elts
            ):
                return {e.value for e in arg.elts}
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                return {arg.value}
    if isinstance(node, ast.Name) and _depth < 1:
        found: List[frozenset] = []
        for sub in walk_in_scope(scope):
            if (
                isinstance(sub, ast.Assign)
                and len(sub.targets) == 1
                and isinstance(sub.targets[0], ast.Name)
                and sub.targets[0].id == node.id
            ):
                got = _mesh_literal_axes(sub.value, scope, _depth + 1)
                if got:
                    found.append(frozenset(got))
        if len(set(found)) == 1:
            return set(found[0])
    return set()


def _declared_axis_names(
    call: ast.Call, scope: ast.AST
) -> Set[str]:
    """The COMPLETE axis universe a shard_map/pmap call binds, when it
    is statically provable — pmap's literal ``axis_name``, or a
    shard_map ``mesh=`` resolving to a ``Mesh(..., ("a", "b"))``
    literal in the same scope. ``in_specs``/``out_specs`` are
    deliberately NOT evidence: specs need not name every mesh axis, so
    judging against them flags perfectly legal replicated-axis
    collectives."""
    if call_name(call) == "pmap":
        axis_name = _kw(call, "axis_name")
        if isinstance(axis_name, ast.Constant) and isinstance(
            axis_name.value, str
        ):
            return {axis_name.value}
        return set()
    mesh = _kw(call, "mesh")
    if mesh is None:
        return set()
    return _mesh_literal_axes(mesh, scope)


class AxisNameMismatch(Rule):
    """A collective inside a mapped body naming an axis the enclosing
    shard_map/pmap does not bind is an unbound-axis error at trace time
    — cheap at your desk, expensive on a hardware day. Judged only
    against a provably complete axis universe (a ``Mesh`` literal or
    pmap's ``axis_name``); meshes built dynamically pass."""

    id = "spmd-axis-name-mismatch"
    severity = "error"
    short = (
        "collective axis_name literal not among the enclosing "
        "shard_map/pmap mesh axes (Mesh literal / pmap axis_name)"
    )
    motivation = (
        "axis names are stringly-typed: a rename that misses one "
        "psum compiles nowhere, and the trace error surfaces only "
        "when the sharded path actually runs (on the TPU day)"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if "shard_map" not in ctx.source and "pmap" not in ctx.source:
            return
        for scope in _scopes(ctx.tree):
            yield from self._check_scope(ctx, scope)

    def _check_scope(
        self, ctx: FileContext, scope: ast.AST
    ) -> Iterator[Finding]:
        for node in walk_in_scope(scope):
            if not isinstance(node, ast.Call) or call_name(node) not in (
                "shard_map", "pmap"
            ):
                continue
            declared = _declared_axis_names(node, scope)
            if not declared:
                continue  # axis universe not statically known
            fn = _resolve_mapped_fn(node, ctx)
            if fn is None:
                continue
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call) or not _is_collective(sub):
                    continue
                axis = _collective_axis_arg(sub)
                if isinstance(axis, ast.Constant) and isinstance(
                    axis.value, str
                ) and axis.value not in declared:
                    yield self.finding(
                        ctx,
                        sub,
                        f"{dotted_name(sub.func)}(..., "
                        f"{axis.value!r}) names an axis the enclosing "
                        f"shard_map/pmap does not bind "
                        f"({sorted(declared)}): unbound axis_name — "
                        "trace-time failure on the sharded path.",
                    )


def _spec_ranks(value: ast.AST) -> Optional[List[int]]:
    """Ranks of P(...)/PartitionSpec(...) literals in an in_specs/
    out_specs value. None when any entry is not a starless P literal
    (unknowable statically)."""
    specs: List[ast.AST]
    if isinstance(value, (ast.Tuple, ast.List)):
        specs = list(value.elts)
    else:
        specs = [value]
    ranks: List[int] = []
    for spec in specs:
        if not (
            isinstance(spec, ast.Call)
            and call_name(spec) in ("P", "PartitionSpec")
            and not spec.keywords
            and all(not isinstance(a, ast.Starred) for a in spec.args)
        ):
            return None
        ranks.append(len(spec.args))
    return ranks


class SpecRankMismatch(Rule):
    """For a rank-preserving mapped body (a lambda that is just a
    psum/ppermute/... or a tiled all_gather), the in_specs and
    out_specs literals must agree on rank; a mismatch is an impossible
    sharding that dies inside shard_map's checks at trace time."""

    id = "spmd-spec-rank-mismatch"
    severity = "error"
    short = (
        "shard_map in_specs/out_specs literal ranks disagree for a "
        "rank-preserving collective body"
    )
    motivation = (
        "spec literals drift when an array gains a dimension; the "
        "error XLA finally raises names pytree internals, not the "
        "spec that went stale"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if "shard_map" not in ctx.source:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or \
                    call_name(node) != "shard_map":
                continue
            if not node.args or not isinstance(node.args[0], ast.Lambda):
                continue
            body = node.args[0].body
            if not (
                isinstance(body, ast.Call)
                and _is_collective(body)
                and self._rank_preserving(body)
            ):
                continue
            in_specs = _kw(node, "in_specs")
            out_specs = _kw(node, "out_specs")
            if in_specs is None or out_specs is None:
                continue
            in_ranks = _spec_ranks(in_specs)
            out_ranks = _spec_ranks(out_specs)
            if in_ranks is None or out_ranks is None:
                continue
            all_ranks = set(in_ranks) | set(out_ranks)
            if len(all_ranks) > 1:
                yield self.finding(
                    ctx,
                    node,
                    f"in_specs ranks {in_ranks} vs out_specs ranks "
                    f"{out_ranks} for a rank-preserving "
                    f"{call_name(body)} body: the specs describe "
                    "arrays of different ranks — one of them is stale.",
                )

    @staticmethod
    def _rank_preserving(body: ast.Call) -> bool:
        name = call_name(body)
        if name in _RANK_PRESERVING:
            return True
        if name == "all_gather":
            tiled = next(
                (kw.value for kw in body.keywords if kw.arg == "tiled"),
                None,
            )
            return isinstance(tiled, ast.Constant) and tiled.value is True
        return False


class ShardMapArity(Rule):
    """``in_specs`` is positional: a tuple literal whose length differs
    from the mapped function's positional arity shards the wrong
    operands (or fails in pytree matching) — the kind of drift a
    refactor that adds one argument leaves behind."""

    id = "spmd-shard-map-arity"
    severity = "error"
    short = (
        "shard_map in_specs tuple length differs from the mapped "
        "function's positional arity"
    )
    motivation = (
        "adding an operand to a mapped solve without extending "
        "in_specs is a silent mis-sharding until the shape check "
        "finally trips far from the cause"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if "shard_map" not in ctx.source:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or \
                    call_name(node) != "shard_map":
                continue
            in_specs = _kw(node, "in_specs")
            if not isinstance(in_specs, (ast.Tuple, ast.List)):
                continue
            fn = _resolve_mapped_fn(node, ctx)
            if fn is None:
                continue
            args = fn.args
            if args.vararg is not None:
                continue  # *args: arity not statically known
            n_params = len(args.posonlyargs) + len(args.args)
            # defaulted params are optional operands: a spec count
            # anywhere in [required, total] is a legal call shape
            n_required = n_params - len(args.defaults)
            n_specs = len(in_specs.elts)
            if not (n_required <= n_specs <= n_params):
                fn_name = getattr(fn, "name", "<lambda>")
                yield self.finding(
                    ctx,
                    node,
                    f"in_specs has {n_specs} entries but mapped "
                    f"function {fn_name!r} takes "
                    f"{n_required}-{n_params} positional arguments — "
                    "the specs and the operands have drifted apart.",
                )


#: calls that place data on devices in operand order
_DEVICE_FEEDERS = frozenset(
    {"device_put", "make_array_from_single_device_arrays"}
)


def _is_set_expr(
    node: ast.AST, scope: ast.AST, _seen: Optional[Set[str]] = None
) -> bool:
    """Is ``node`` (a loop/comprehension iterable) a hash-ordered set —
    a set literal/comprehension, a set()/frozenset() call, or a name
    assigned one of those in this scope?"""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and call_name(node) in (
        "set", "frozenset"
    ) and dotted_name(node.func) in ("set", "frozenset"):
        return True
    if isinstance(node, ast.Name):
        seen = _seen if _seen is not None else set()
        if node.id in seen:
            return False  # self-referential assignment: give up
        seen.add(node.id)
        for sub in ast.walk(scope):
            if (
                isinstance(sub, ast.Assign)
                and len(sub.targets) == 1
                and isinstance(sub.targets[0], ast.Name)
                and sub.targets[0].id == node.id
                and _is_set_expr(sub.value, scope, seen)
            ):
                return True
    return False


class UnorderedCollectiveOperand(Rule):
    """Set iteration order is hash order: two processes building device
    operands from "the same" set can disagree on element order, so the
    collectives see permuted operands — cross-host nondeterminism that
    no single-host test reproduces. Iterate ``sorted(...)`` instead."""

    id = "spmd-unordered-collective-operand"
    severity = "error"
    short = (
        "set iteration feeding device_put / collective operands "
        "(hash order differs across processes)"
    )
    motivation = (
        "per-host operand order IS program semantics under SPMD; a "
        "set-ordered device_put loop is the distributed twin of the "
        "round-5 nondeterministic-gather bug"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not any(f in ctx.source for f in _DEVICE_FEEDERS) and not any(
            c in ctx.source for c in _COLLECTIVES
        ):
            return
        for scope in _scopes(ctx.tree):
            for node in walk_in_scope(scope):
                body: List[ast.AST]
                if isinstance(node, ast.For):
                    if not _is_set_expr(node.iter, scope):
                        continue
                    body = list(node.body)
                elif isinstance(
                    node, (ast.ListComp, ast.GeneratorExp, ast.SetComp)
                ):
                    if not any(
                        _is_set_expr(gen.iter, scope)
                        for gen in node.generators
                    ):
                        continue
                    body = [node.elt]
                else:
                    continue
                for part in body:
                    for sub in ast.walk(part):
                        if not isinstance(sub, ast.Call):
                            continue
                        if call_name(sub) in _DEVICE_FEEDERS or \
                                _is_collective(sub):
                            yield self.finding(
                                ctx,
                                sub,
                                f"{call_name(sub)}(...) fed from set "
                                "iteration: hash order differs across "
                                "processes, so hosts disagree on "
                                "operand order — iterate "
                                "sorted(<set>) instead.",
                            )


#: dotted call names whose value differs per host/run
_NONDETERMINISTIC_SEEDS = frozenset(
    {
        "time.time", "time.time_ns", "time.monotonic",
        "os.getpid", "getpid", "os.urandom", "urandom",
        "uuid.uuid4", "uuid4", "secrets.token_hex", "secrets.token_bytes",
        "secrets.randbits", "getrandbits",
    }
)


class HostDependentRng(Rule):
    """RNG seeds derived from wall clocks/pids diverge across hosts and
    runs; inside a sharded (shard_map/pmap-mapped) function a
    ``process_index()``-derived seed makes each host sample different
    randomness in a program that must be identical everywhere."""

    id = "spmd-host-dependent-rng"
    severity = "error"
    short = (
        "PRNGKey seeded from time/pid/urandom (anywhere) or "
        "process_index (inside a sharded function)"
    )
    motivation = (
        "ALX-style sharded ALS initializes factor shards from RNG; a "
        "host-divergent seed silently trains a different model per "
        "host and the first symptom is an accuracy regression"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if "PRNGKey" not in ctx.source and "random.key" not in ctx.source:
            return
        mapped = _mapped_functions(ctx)

        def inside_mapped(node: ast.AST) -> bool:
            return any(
                any(sub is node for sub in ast.walk(fn)) for fn in mapped
            )

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            dn = dotted_name(node.func)
            if not (
                name == "PRNGKey" or dn.endswith("random.key")
            ):
                continue
            seed = node.args[0] if node.args else _kw(node, "seed")
            if seed is None:
                continue
            for sub in ast.walk(seed):
                if not isinstance(sub, ast.Call):
                    continue
                sub_dn = dotted_name(sub.func)
                if sub_dn in _NONDETERMINISTIC_SEEDS:
                    yield self.finding(
                        ctx,
                        node,
                        f"PRNGKey seeded from {sub_dn}(...): the seed "
                        "differs per host and per run — derive seeds "
                        "from configuration (and fold in a *rank* only "
                        "deliberately, outside sharded bodies).",
                    )
                    break
                if _is_host_divergent_call(sub) and inside_mapped(node):
                    yield self.finding(
                        ctx,
                        node,
                        "PRNGKey seeded from process_index() inside a "
                        "sharded function: each host samples different "
                        "randomness in a program that must be "
                        "identical everywhere — seed outside the "
                        "mapped body and shard the key explicitly.",
                    )
                    break


class CollectiveMissingAxis(Rule):
    """``psum``/``all_gather``/... require their axis argument; a call
    that omits it raises ``TypeError`` at TRACE time — and a mesh-gated
    sharded body (``mesh is not None`` paths like the sharded ALS
    trainer) only traces when the sharded path runs, i.e. on hardware
    you get for a day. Judged only inside shard_map/pmap-mapped bodies:
    outside them the same omission fails the first unit test that calls
    the function."""

    id = "spmd-collective-missing-axis"
    severity = "error"
    short = (
        "collective (psum/all_gather/...) inside a shard_map/pmap body "
        "with no axis argument — trace-time TypeError on the sharded path"
    )
    motivation = (
        "the sharded ALS data plane traces its collectives only under a "
        "real mesh; an axis dropped in a refactor compiles nowhere and "
        "surfaces on the hardware day"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if "shard_map" not in ctx.source and "pmap" not in ctx.source:
            return
        seen: Set[int] = set()  # a body mapped twice is judged once
        for fn in _mapped_functions(ctx):
            for sub in ast.walk(fn):
                if (
                    not isinstance(sub, ast.Call)
                    or not _is_collective(sub)
                    or id(sub) in seen
                ):
                    continue
                seen.add(id(sub))
                if any(isinstance(a, ast.Starred) for a in sub.args) or any(
                    kw.arg is None for kw in sub.keywords
                ):
                    # *args/**kwargs splats at the collective itself are
                    # judged by the package-level twin of this rule
                    # (rules_flow.CollectiveMissingAxisDeep), which can
                    # see whether the mapped body's own varargs actually
                    # carry an axis — here the call is not statically
                    # knowable, so stay silent rather than guess
                    continue
                if _collective_axis_arg(sub) is None:
                    yield self.finding(
                        ctx,
                        sub,
                        f"{dotted_name(sub.func)}(...) inside a "
                        "shard_map/pmap-mapped body has no axis "
                        "argument: the collective cannot name the mesh "
                        "axis it reduces over and raises TypeError the "
                        "first time the SHARDED path traces — pass the "
                        "axis name explicitly.",
                    )


#: dtypes narrower than f32 — writing one of these into serve/train
#: state without a numeric gate is silent precision loss. Index dtypes
#: (uint16/int32/int64) are deliberately absent: narrowing an *id* is
#: lossless below the table size, and the gather paths pack ids that
#: way on purpose.
_SUB_F32_DTYPES = frozenset(
    {
        "int8", "uint8", "int4", "uint4",
        "bfloat16", "float16", "half",
        "float8_e4m3fn", "float8_e5m2", "float8_e4m3",
        "float8_e4m3fnuz", "float8_e5m2fnuz",
    }
)

#: substrings that put a function on the serve/train data path — the
#: scopes where a narrowed value reaches a user or a model ("serv"
#: catches serve/serving/server)
_PATH_MARKERS = ("serv", "train", "predict")


def _is_gate_call(name: str) -> bool:
    """Does this call name look like a numeric gate — an exactness or
    tolerance check that licenses a precision cut in its scope?"""
    return (
        name.endswith("_gate")
        or name == "rmse"
        or "topk_match" in name
        or name in ("allclose", "isclose", "assert_allclose")
    )


def _dtype_tail(node: ast.AST) -> str:
    """The dtype a cast targets, as a bare name: ``jnp.int8`` → "int8",
    ``"bfloat16"`` → "bfloat16"; "" when not statically resolvable (a
    variable like ``gdt`` stays silent rather than guessed)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    dn = dotted_name(node)
    if dn and "." in dn:
        return dn.rsplit(".", 1)[-1]
    return ""


def _downcast_dtype(node: ast.Call) -> str:
    """The sub-f32 dtype this call casts to, or "" if it is not a
    statically-resolvable downcast (``x.astype(jnp.int8)``,
    ``lax.convert_element_type(x, jnp.bfloat16)``, string forms)."""
    name = call_name(node)
    target: Optional[ast.AST] = None
    if name == "astype":
        target = node.args[0] if node.args else _kw(node, "dtype")
    elif name == "convert_element_type":
        if len(node.args) > 1:
            target = node.args[1]
        else:
            target = _kw(node, "new_dtype")
    if target is None:
        return ""
    tail = _dtype_tail(target)
    return tail if tail in _SUB_F32_DTYPES else ""


class UnguardedDowncast(Rule):
    """A cast below f32 inside a serve/train/predict-marked function
    with no gate-shaped call in the same scope: precision left the data
    path and nothing measured what it cost. The quantization contract
    (docs/quantization.md) is cut-precision-AND-measure in one scope —
    ``quant/table.py``'s ``quantize_serving_table`` inlines its int8
    encode next to ``topk_match_gate`` for exactly this adjacency, and
    the tests mutation-pin it as the clean exemplar."""

    id = "spmd-unguarded-downcast"
    severity = "error"
    short = (
        "sub-f32 cast (int8/bf16/fp8/...) in a serve/train-marked "
        "function with no gate-shaped check in scope"
    )
    motivation = (
        "the bf16 bench gate and the int8 serving gate both exist "
        "because an unmeasured narrowing ships silent accuracy loss; "
        "a downcast that dodges both is the regression they guard "
        "against, written fresh"
    )

    _MARKERS = ("astype", "convert_element_type")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not any(m in ctx.source for m in self._MARKERS):
            return
        for scope in _scopes(ctx.tree):
            if not isinstance(
                scope, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            lowered = scope.name.lower()
            if not any(m in lowered for m in _PATH_MARKERS):
                continue
            if any(
                isinstance(node, ast.Call)
                and _is_gate_call(call_name(node))
                for node in walk_in_scope(scope)
            ):
                continue  # a gate in scope licenses the cut
            for node in walk_in_scope(scope):
                if not isinstance(node, ast.Call):
                    continue
                dtype = _downcast_dtype(node)
                if dtype:
                    yield self.finding(
                        ctx,
                        node,
                        f"cast to {dtype} inside "
                        f"{scope.name!r} with no gate-shaped check "
                        "(*_gate / rmse / topk_match / allclose) in "
                        "scope: precision leaves the serve/train path "
                        "unmeasured — gate the narrowed value against "
                        "its f32 twin in the same scope "
                        "(docs/quantization.md#gate).",
                    )


RULES: List[Rule] = [
    CollectiveHostBranch(),
    AxisNameMismatch(),
    SpecRankMismatch(),
    ShardMapArity(),
    UnorderedCollectiveOperand(),
    HostDependentRng(),
    CollectiveMissingAxis(),
    UnguardedDowncast(),
]
