"""Family G — cross-file flow rules (``flow-*``): the interprocedural
closure of the per-file families, judged over the package-wide fact
tables of :mod:`packagectx` instead of a single file's AST.

Every rule here follows one resolution contract (docs/lint.md#family-g):
a call site is resolved through the import table / single-inheritance
method resolution **one level deep** to a function whose facts were
extracted from its own file; the callee's *direct* behavior (a blocking
call, a collective, a ``deadline`` parameter) is then judged at the
caller's line. A reference that does not resolve inside the lint scope
is not judged — stdlib and third-party callees get the benefit of the
doubt, and a two-hop chain (helper calling helper calling ``sleep``) is
out of contract by design: one level keeps every verdict explainable by
exactly two source locations, both named in the message.

Findings are always attributed to the file whose facts are being
judged, so suppressions stay file-local and the incremental cache can
key flow results on (file hash, import-closure hash).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Set, Tuple

from .engine import FileContext, Finding, Rule
from .packagectx import (
    PackageContext,
    is_lifecycle_method,
    single_file_context,
)


class FlowRule(Rule):
    """Base for package-scope rules: ``check_module`` judges one
    module's facts against the package context. ``check(ctx)`` keeps
    the per-file entry point working (``lint_file`` on fixtures /
    single files) by wrapping the file in a one-module package."""

    scope = "package"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        module, pctx = single_file_context(ctx)
        yield from self.check_module(module, pctx)

    def check_module(
        self, module: str, pctx: PackageContext
    ) -> Iterator[Finding]:
        raise NotImplementedError

    def flow_finding(
        self, facts: dict, line: int, col: int, message: str
    ) -> Finding:
        return Finding(
            rule_id=self.id,
            path=facts["path"],
            line=line,
            col=col,
            message=message,
            severity=self.severity,
        )


def _where(pctx: PackageContext, mod: str, fn: dict, line: int) -> str:
    path = pctx.modules[mod]["path"]
    return f"{path}:{line}"


class FlowBlockingUnderLock(FlowRule):
    """The interprocedural closure of ``conc-blocking-under-lock``: the
    blocking call is one resolution hop away — a helper defined
    anywhere in the package that sleeps / joins / does socket or
    subprocess I/O, invoked while a lock is held. The per-file rule
    sees only lexically-direct blocking calls; refactoring the blocking
    work into a helper (the natural cleanup!) used to move the convoy
    out of the linter's sight without moving it out of the critical
    section."""

    id = "flow-blocking-under-lock"
    severity = "error"
    short = (
        "call under a held lock resolves to a package helper that "
        "blocks (sleep/HTTP/fsync/join/subprocess) in its own body"
    )
    motivation = (
        "conc-blocking-under-lock's documented blind spot: the oplog/"
        "rollout persistence paths kept their locks honest by inlining "
        "I/O where the rule could see it — a helper extraction would "
        "have un-gated them silently"
    )

    def check_module(
        self, module: str, pctx: PackageContext
    ) -> Iterator[Finding]:
        facts = pctx.modules[module]
        for fn in facts["functions"].values():
            for call in fn["calls"]:
                if not call["locks"]:
                    continue
                hit = pctx.resolve_call(module, fn["cls"], call["ref"])
                if hit is None:
                    continue
                cal_mod, qual, callee = hit
                if callee is None or not callee["blocking"]:
                    continue
                shown, bline = callee["blocking"][0]
                locks = ", ".join(call["locks"])
                yield self.flow_finding(
                    facts, call["line"], call["col"],
                    f"{cal_mod}.{qual}(...) called while holding "
                    f"{locks}: the callee blocks on {shown} "
                    f"({_where(pctx, cal_mod, callee, bline)}) — every "
                    "thread needing the lock waits out that I/O; "
                    "snapshot state under the lock and call the helper "
                    "after releasing it.",
                )


class FlowDeadlineDropped(FlowRule):
    """A deadline in hand is a contract to bound *all* remaining work;
    a call that reaches a deadline-capable package callee without
    forwarding it silently un-bounds that leg (the callee falls back to
    its default timeout — or none), which is exactly how a 250 ms
    budget turns into a 30 s stall on the slowest shard. The router and
    partitioned-write retry paths thread ``deadline=`` by hand; this
    rule makes the discipline mechanical.

    Judged only when the caller is deadline-scoped (a ``deadline``
    parameter, a ``current_deadline()`` / ``Deadline.from_header`` /
    ``Deadline.after_ms`` binding, or a ``with deadline_scope(...)``
    block) and the callee resolves in-package with a ``deadline``
    parameter (or a *required* ``timeout`` parameter). Exempt: the
    callee reads the ambient ``current_deadline()`` itself — the
    contextvar-propagation idiom ``storage/remote.py`` uses — or the
    call forwards via ``*args``/``**kwargs`` (benefit of the doubt)."""

    id = "flow-deadline-dropped"
    severity = "error"
    short = (
        "deadline-scoped caller invokes a package callee that accepts "
        "deadline/timeout without forwarding it"
    )
    motivation = (
        "the fan-out budget bugs of the router rounds: one leg that "
        "forgets to pass the deadline waits out a dead peer's full "
        "socket timeout while the request's budget is long gone"
    )

    #: parameter names that make a callee deadline-capable
    _PARAM = "deadline"

    def check_module(
        self, module: str, pctx: PackageContext
    ) -> Iterator[Finding]:
        facts = pctx.modules[module]
        for fn in facts["functions"].values():
            if not fn["has_deadline"]:
                continue
            for call in fn["calls"]:
                hit = pctx.resolve_call(module, fn["cls"], call["ref"])
                if hit is None:
                    continue
                cal_mod, qual, callee = hit
                if callee is None:
                    continue
                pname = self._capable_param(callee)
                if pname is None:
                    continue
                if self._forwarded(call, callee, pname):
                    continue
                if callee["ambient_deadline"]:
                    continue  # reads current_deadline() itself
                yield self.flow_finding(
                    facts, call["line"], call["col"],
                    f"{cal_mod}.{qual}(...) accepts `{pname}` but this "
                    "deadline-scoped call site does not forward one: "
                    "the leg runs unbounded while the caller's budget "
                    f"ticks — pass {pname}=..., or have the callee read "
                    "current_deadline().",
                )

    def _capable_param(self, callee: dict) -> Optional[str]:
        if self._PARAM in callee["params"] or \
                self._PARAM in callee["kwonly"]:
            return self._PARAM
        # a REQUIRED timeout parameter is the same contract under the
        # older name; optional timeouts (timeout=30.0 defaults) are
        # family-C territory and judging them here would flag every
        # caller that deliberately rides the default
        params = callee["params"]
        if "timeout" in params:
            idx = params.index("timeout")
            if idx < len(params) - callee["defaults"]:
                return "timeout"
        if "timeout" in callee["kwonly"] and \
                "timeout" not in callee["kwonly_defaulted"]:
            return "timeout"
        return None

    @staticmethod
    def _forwarded(call: dict, callee: dict, pname: str) -> bool:
        if pname in call["kws"] or call["kwsplat"] or call["star"]:
            return True
        if pname in callee["params"]:
            return call["nargs"] > callee["params"].index(pname)
        return False


class FlowThreadLeak(FlowRule):
    """A worker thread stored on ``self`` and started must have a stop
    story reachable from the class's lifecycle methods (``close`` /
    ``server_close`` / ``shutdown`` / ``stop*`` / ``__exit__``),
    resolved through single-inheritance base classes. Accepted evidence
    for a thread attribute: a lifecycle method (or a self-method it
    calls, one hop) joins it, references it (sentinel draining counts —
    ``_ShardLegPool.stop`` pushes stop sentinels through the queue the
    workers drain), or sets one of the class's ``threading.Event``
    attributes (the loop-flag idiom ``obs/slo.py`` and the replica
    tailer use). No lifecycle method at all, or none that touches the
    worker or an event, and the thread outlives every ``close()`` —
    the leak that keeps test processes and rolling restarts hanging."""

    id = "flow-thread-leak"
    severity = "error"
    short = (
        "Thread/Timer stored on self and started, with no stop/join "
        "reachable from close/server_close/shutdown/stop* (bases "
        "included)"
    )
    motivation = (
        "every long-lived control-plane class in the tree (SLO ticker, "
        "continuous controller, replica tailer, router leg pools) had "
        "to get this right by review; a worker added without a stop "
        "story only surfaces as a hung shutdown in production"
    )

    def check_module(
        self, module: str, pctx: PackageContext
    ) -> Iterator[Finding]:
        facts = pctx.modules[module]
        for cname, cfacts in facts["classes"].items():
            if cfacts["thread_subclass"]:
                continue  # it IS the worker; its owner is judged
            if not cfacts["threads"] or not cfacts["started"]:
                continue
            chain = list(pctx.class_chain(module, cname))
            event_attrs: Set[str] = set()
            for _m, _n, cf in chain:
                event_attrs |= {
                    a for a, k in cf.get("locks", {}).items()
                    if k == "event"
                }
            lifecycle = self._lifecycle_functions(pctx, chain)
            if not lifecycle:
                for attr, line in cfacts["threads"]:
                    yield self.flow_finding(
                        facts, line, 1,
                        f"{cname} starts a worker thread on "
                        f"self.{attr} but defines no close/shutdown/"
                        "stop method (own or inherited in-package): "
                        "the thread outlives the object — add a stop "
                        "method that signals and joins it.",
                    )
                continue
            reach = self._reachable(pctx, chain, lifecycle)
            for attr, line in cfacts["threads"]:
                if any(
                    attr in fn["joins"]
                    or attr in fn["self_reads"]
                    or (event_attrs and set(fn["event_sets"])
                        & event_attrs)
                    for fn in reach
                ):
                    continue
                names = sorted({fn["name"] for fn in lifecycle})
                yield self.flow_finding(
                    facts, line, 1,
                    f"{cname} starts a worker thread on self.{attr} "
                    f"but no lifecycle method ({', '.join(names)}) "
                    "joins it, references it, or sets a stop Event: "
                    "close() returns with the worker still running — "
                    "signal and join the thread in teardown.",
                )

    @staticmethod
    def _lifecycle_functions(
        pctx: PackageContext,
        chain: List[Tuple[str, str, dict]],
    ) -> List[dict]:
        out: List[dict] = []
        seen: Set[Tuple[str, str]] = set()
        for mod, cname, cfacts in chain:
            for meth in cfacts["methods"]:
                if not is_lifecycle_method(meth):
                    continue
                key = (mod, f"{cname}.{meth}")
                fn = pctx.modules[mod]["functions"].get(key[1])
                if fn and key not in seen:
                    seen.add(key)
                    out.append(fn)
        return out

    @staticmethod
    def _reachable(
        pctx: PackageContext,
        chain: List[Tuple[str, str, dict]],
        lifecycle: List[dict],
    ) -> List[dict]:
        """Lifecycle methods plus the self-methods they call (one hop,
        resolved through the chain) — the scope searched for stop
        evidence."""
        mod0, cls0 = chain[0][0], chain[0][1]
        out = list(lifecycle)
        seen = {id(fn) for fn in out}
        for fn in lifecycle:
            for call in fn["calls"]:
                kind, _, rest = call["ref"].partition(":")
                if kind != "self":
                    continue
                hit = pctx.resolve_method(mod0, cls0, rest)
                if hit is not None and id(hit[2]) not in seen:
                    seen.add(id(hit[2]))
                    out.append(hit[2])
        return out


class CollectiveMissingAxisDeep(FlowRule):
    """The call-graph extension of ``spmd-collective-missing-axis``
    (same rule id — one catalog entry, one suppression token): a
    collective hidden one call deep inside a shard_map/pmap body is
    judged too. Three shapes the per-file rule cannot see:

    - the mapped body lives in another module (``shard_map(ops.body)``);
    - the mapped body calls a package helper whose collective omits the
      axis;
    - the helper forwards its own ``*args``/``**kwargs`` into the
      collective's axis slot — the per-file rule's documented skip. The
      call site decides: a site that provably forwards nothing extra
      (no spare positionals, no ``axis_name=``, no splat) makes the
      missing axis a static fact and fires; a site that feeds the splat
      is clean."""

    id = "spmd-collective-missing-axis"
    severity = "error"
    short = (
        "collective with no axis reached through the call graph (mapped "
        "body in another module, helper call, *args forwarding)"
    )
    motivation = (
        "the per-file rule shipped with '*args/**kwargs calls pass' in "
        "its own comment; the call graph makes the forwarding judgeable "
        "instead of exempt"
    )

    def check_module(
        self, module: str, pctx: PackageContext
    ) -> Iterator[Finding]:
        facts = pctx.modules[module]
        seen: Set[Tuple] = set()
        for mapped in facts["mapped"]:
            hit = pctx.resolve_call(module, None, mapped["ref"])
            if hit is None:
                continue
            body_mod, body_qual, body = hit
            if body is None:
                continue
            local_body = body_mod == module
            # the body's own collectives: the per-file rule already
            # judges them when the body is in the mapping file; when it
            # is not, this is the only judge they get
            if not local_body:
                for cf in body["collectives"]:
                    if cf["ok"] or cf["vararg"]:
                        continue
                    key = (body_mod, cf["line"], "own")
                    if key in seen:
                        continue
                    seen.add(key)
                    yield self.flow_finding(
                        facts, mapped["line"], 1,
                        f"shard_map/pmap maps {body_mod}.{body_qual}, "
                        f"whose {cf['name']}(...) at "
                        f"{_where(pctx, body_mod, body, cf['line'])} "
                        "has no axis argument: trace-time TypeError "
                        "the first time the sharded path runs.",
                    )
            # one hop: helpers the mapped body calls
            for call in body["calls"]:
                hop = pctx.resolve_call(body_mod, body["cls"], call["ref"])
                if hop is None:
                    continue
                helper_mod, helper_qual, helper = hop
                if helper is None:
                    continue
                for cf in helper["collectives"]:
                    if cf["ok"]:
                        continue
                    if cf["vararg"] and self._site_feeds_axis(call, helper):
                        continue
                    key = (helper_mod, cf["line"], call["line"], body_mod)
                    if key in seen:
                        continue
                    seen.add(key)
                    line, col = (
                        (call["line"], call["col"]) if local_body
                        else (mapped["line"], 1)
                    )
                    how = (
                        "forwards no axis into its *args/**kwargs"
                        if cf["vararg"]
                        else "omits the axis outright"
                    )
                    yield self.flow_finding(
                        facts, line, col,
                        f"{helper_mod}.{helper_qual}(...) called from "
                        "a shard_map/pmap-mapped body reaches "
                        f"{cf['name']}(...) at "
                        f"{_where(pctx, helper_mod, helper, cf['line'])} "
                        f"with no axis ({how}): trace-time TypeError "
                        "on the sharded path — pass the axis name "
                        "through.",
                    )

    @staticmethod
    def _site_feeds_axis(call: dict, helper: dict) -> bool:
        """Does this call site put anything into the helper's
        ``*args``/``**kwargs`` that could be the axis?"""
        if call["star"] or call["kwsplat"]:
            return True
        if "axis_name" in call["kws"]:
            return True
        extra_kws = set(call["kws"]) - set(helper["params"]) - set(
            helper["kwonly"]
        )
        if extra_kws and helper["kwarg"]:
            return True
        return call["nargs"] > len(helper["params"])


RULES: List[Rule] = [
    FlowBlockingUnderLock(),
    FlowDeadlineDropped(),
    FlowThreadLeak(),
    CollectiveMissingAxisDeep(),
]
