"""Family E — lock-discipline / race hygiene rules, applied package-wide.

PRs 2-5 made the control plane genuinely multi-threaded: shadow pools
(``rollout/manager.py``), replica tailers (``storage/replica.py``),
breaker registries (``storage/remote.py``), micro-batch dispatchers
(``workflow/batching.py``), metrics scrape threads (``obs/metrics.py``).
The bug classes that code shape carries — an attribute guarded by a lock
in one method and read bare from another thread, a lock leaked on an
exception path, a blocking call made while holding a hot lock, two locks
taken in opposite orders — are mechanical and visible at AST level, so
like the Mosaic/jit/robust/obs families they are caught before the
first stuck scrape or deadlocked drain:

- ``conc-unguarded-attr``: per-class inference — an attribute some
  method writes under ``with self._lock:`` is this class's lock-guarded
  state; accessing it bare from a cross-thread entry point (a
  ``threading.Thread``/``Timer`` target, an executor ``submit``, a
  ``gauge_callback``) is a data race.
- ``conc-acquire-no-with``: ``lock.acquire()`` outside a ``with`` and
  without a ``finally: release()`` leaks the lock on the first
  exception — every later acquirer hangs forever.
- ``conc-blocking-under-lock``: a blocking call (sleep, HTTP, fsync,
  ``Future.result``, ``thread.join``, subprocess) made while holding a
  lock turns that lock into a convoy: every thread needing it waits out
  the I/O.
- ``conc-lock-order``: ``with A: ... with B:`` in one place and
  ``with B: ... with A:`` in another is a textbook deadlock.
- ``conc-module-mutable``: a module-level dict/list/set mutated inside
  a function without a module-level lock held — request-time mutation
  of an import-time registry races every server thread.
- ``conc-contextvar-thread-hop``: contextvars do not cross threads; a
  thread-entry function reading an ambient contextvar
  (``current_context()``/``current_deadline()``/``<var>.get()``) sees
  the *worker's* empty context, not the request's. Capture at submit
  time and pass explicitly (the ``obs/trace.py`` discipline).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .engine import (
    ClassScope,
    FileContext,
    Finding,
    Rule,
    _self_attr,
    call_name,
    dotted_name,
)

#: entry-point call shapes that hand a callable to another thread
_ENTRY_THREAD_CTORS = frozenset({"Thread"})


def _parent_map(ctx: FileContext) -> Dict[ast.AST, ast.AST]:
    """Child → parent for the whole tree, computed once per file and
    stashed on the context (four family-E rules need it; rebuilding per
    rule made the package sweep measurably slower)."""
    cached = getattr(ctx, "_conc_parents", None)
    if cached is None:
        cached = {}
        for node in ast.walk(ctx.tree):
            for child in ast.iter_child_nodes(node):
                cached[child] = node
        ctx._conc_parents = cached
    return cached


def _enclosing(node: ast.AST, parents: Dict[ast.AST, ast.AST], kinds):
    cur = parents.get(node)
    while cur is not None and not isinstance(cur, kinds):
        cur = parents.get(cur)
    return cur


def _class_scope_of(
    node: ast.AST, ctx: FileContext, parents: Dict[ast.AST, ast.AST]
) -> Optional[ClassScope]:
    cls_node = node if isinstance(node, ast.ClassDef) else _enclosing(
        node, parents, ast.ClassDef
    )
    for cs in ctx.classes:
        if cs.node is cls_node:
            return cs
    return None


def _resolve_callable(
    site: ast.Call,
    value: ast.AST,
    ctx: FileContext,
    parents: Dict[ast.AST, ast.AST],
) -> Optional[ast.AST]:
    """The function/lambda node a callable reference points at, when it
    is visible in this file: a lambda literal, ``self._method``, a
    nested ``def`` in the enclosing function, or a module-level def."""
    if isinstance(value, ast.Lambda):
        return value
    attr = _self_attr(value)
    if attr:
        cs = _class_scope_of(site, ctx, parents)
        if cs is not None:
            return cs.methods.get(attr)
        return None
    if isinstance(value, ast.Name):
        fn = _enclosing(
            site, parents, (ast.FunctionDef, ast.AsyncFunctionDef)
        )
        if fn is not None:
            for node in ast.walk(fn):
                if isinstance(node, ast.FunctionDef) and \
                        node.name == value.id:
                    return node
        for node in ctx.tree.body:
            if isinstance(node, ast.FunctionDef) and node.name == value.id:
                return node
    return None


def thread_entries(
    ctx: FileContext, parents: Dict[ast.AST, ast.AST]
) -> List[Tuple[ast.AST, str]]:
    """Functions/lambdas in this file that execute on another thread:
    ``Thread(target=f)`` / ``Timer(delay, f)`` targets, ``pool.submit(f,
    ...)`` submissions, ``gauge_callback(name, f)`` scrape callbacks,
    and ``run`` methods of ``threading.Thread`` subclasses. Returns
    (node, how) pairs, deduplicated."""
    out: List[Tuple[ast.AST, str]] = []
    seen: Set[int] = set()

    def add(node: Optional[ast.AST], how: str) -> None:
        if node is not None and id(node) not in seen:
            seen.add(id(node))
            out.append((node, how))

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        refs: List[Tuple[ast.AST, str]] = []
        if name in _ENTRY_THREAD_CTORS:
            for kw in node.keywords:
                if kw.arg == "target":
                    refs.append((kw.value, "Thread target"))
        elif name == "Timer":
            if len(node.args) >= 2:
                refs.append((node.args[1], "Timer callback"))
            for kw in node.keywords:
                if kw.arg == "function":
                    refs.append((kw.value, "Timer callback"))
        elif name == "submit":
            if node.args:
                refs.append((node.args[0], "executor submission"))
        elif name == "gauge_callback":
            if len(node.args) >= 2:
                refs.append((node.args[1], "scrape-time gauge callback"))
            for kw in node.keywords:
                if kw.arg == "fn":
                    refs.append((kw.value, "scrape-time gauge callback"))
        for value, how in refs:
            add(_resolve_callable(node, value, ctx, parents), how)
    for cs in ctx.classes:
        if cs.is_thread_subclass and "run" in cs.methods:
            add(cs.methods["run"], "threading.Thread subclass run()")
    return out


def _preceding_sibling(
    stmt: ast.AST, parents: Dict[ast.AST, ast.AST]
) -> Optional[ast.AST]:
    """The statement directly before ``stmt`` in its parent block, or
    None when it opens the block."""
    parent = parents.get(stmt)
    if parent is None:
        return None
    for field in ("body", "orelse", "finalbody"):
        seq = getattr(parent, field, None)
        if isinstance(seq, list) and stmt in seq:
            idx = seq.index(stmt)
            return seq[idx - 1] if idx > 0 else None
    return None


def _iter_scope_with_lockstate(
    root: ast.AST, holds
) -> Iterator[Tuple[ast.AST, Set[str]]]:
    """Yield (node, frozenset-of-held-lock-names) for every node in
    ``root``'s scope. Nested function/class bodies are visited too, but
    their lock state restarts empty: an enclosing ``with`` wraps their
    *definition*, not their execution."""

    def visit(node: ast.AST, held: Set[str]) -> Iterator:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                 ast.ClassDef),
            ):
                yield from visit(child, set())
                continue
            now = held
            if isinstance(child, ast.With):
                got = holds(child)
                if got:
                    now = held | got
            yield child, now
            yield from visit(child, now)

    yield from visit(root, set())


class UnguardedAttr(Rule):
    """An attribute this class writes under one of its own locks,
    accessed without any of them from a function that runs on another
    thread. The lock-guarded write is the class declaring "this state
    is shared"; the bare cross-thread access is the race."""

    id = "conc-unguarded-attr"
    severity = "error"
    short = (
        "lock-guarded attribute accessed bare in a thread target / "
        "timer / submit / gauge callback"
    )
    motivation = (
        "the PR-4/PR-5 control plane reads state from scrape threads "
        "and pool workers; an attr written under self._lock in one "
        "method and read bare on those threads is a torn-read race "
        "that only fires under production concurrency"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # cheap bail: no class in this file has lock-guarded state
        if not any(cs.guarded_writes for cs in ctx.classes):
            return
        parents = _parent_map(ctx)
        for entry, how in thread_entries(ctx, parents):
            cs = _class_scope_of(entry, ctx, parents)
            if cs is None or not cs.guarded_writes:
                continue
            mutexes = cs.mutex_attrs()

            def holds(w: ast.With) -> Set[str]:
                return {
                    _self_attr(item.context_expr)
                    for item in w.items
                    if _self_attr(item.context_expr) in mutexes
                }

            reported: Set[str] = set()
            for node, held in _iter_scope_with_lockstate(entry, holds):
                if held or not isinstance(node, ast.Attribute):
                    continue
                attr = _self_attr(node)
                if (
                    attr
                    and attr in cs.guarded_writes
                    and attr not in reported
                ):
                    reported.add(attr)
                    yield self.finding(
                        ctx,
                        node,
                        f"self.{attr} is written under a lock elsewhere "
                        f"in {cs.name} but accessed without one in a "
                        f"{how} — guard the access (or snapshot the "
                        "value under the lock before the thread hop).",
                    )


class AcquireNoWith(Rule):
    """``lock.acquire()`` without ``with`` or a ``finally: release()``:
    the first exception between acquire and release leaks the lock and
    every later acquirer blocks forever. Semaphores/Events are exempt —
    cross-thread hand-off (acquire here, release on the worker) is what
    they are for."""

    id = "conc-acquire-no-with"
    severity = "error"
    short = (
        "lock.acquire() outside `with` and without a finally-release "
        "(lock leak on exception)"
    )
    motivation = (
        "a leaked lock is a whole-process hang with a clean stack "
        "trace pointing nowhere; `with lock:` makes the leak "
        "impossible to write"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ".acquire(" not in ctx.source:  # cheap bail
            return
        parents = _parent_map(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not isinstance(fn, ast.Attribute) or fn.attr != "acquire":
                continue
            base = dotted_name(fn.value)
            if not base:
                continue  # chained/derived receivers: not a plain lock ref
            if self._is_handoff_primitive(base, node, ctx, parents):
                continue
            parent = parents.get(node)
            if isinstance(parent, ast.withitem):
                continue  # `with pool.acquire() as x:` — scoped by the with
            scope = _enclosing(
                node, parents, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) or ctx.tree
            if self._released_in_finally(scope, base, node, parents):
                continue
            yield self.finding(
                ctx,
                node,
                f"{base}.acquire() without `with` or a finally-release: "
                "an exception before release() leaks the lock and hangs "
                f"every later acquirer — use `with {base}:`.",
            )

    @staticmethod
    def _is_handoff_primitive(
        base: str,
        node: ast.AST,
        ctx: FileContext,
        parents: Dict[ast.AST, ast.AST],
    ) -> bool:
        attr = base[len("self."):] if base.startswith("self.") else ""
        if attr:
            cs = _class_scope_of(node, ctx, parents)
            if cs is not None and cs.lock_attrs.get(attr) in (
                "semaphore", "event"
            ):
                return True
        return ctx.module_locks.get(base) in ("semaphore", "event")

    @staticmethod
    def _released_in_finally(
        scope: ast.AST,
        base: str,
        acquire: ast.AST,
        parents: Dict[ast.AST, ast.AST],
    ) -> bool:
        """True only when a try/finally that releases ``base`` actually
        *covers* the acquire: the acquire is inside the try body, or is
        the statement immediately before the try (the classic
        ``lock.acquire()`` / ``try: ... finally: release()`` idiom). A
        finally elsewhere in the function protects nothing between the
        acquire and itself — the leak the rule exists to catch."""
        for node in ast.walk(scope):
            if not isinstance(node, ast.Try) or not node.finalbody:
                continue
            releases = any(
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "release"
                and dotted_name(sub.func.value) == base
                for stmt in node.finalbody
                for sub in ast.walk(stmt)
            )
            if not releases:
                continue
            if any(
                sub is acquire
                for stmt in node.body
                for sub in ast.walk(stmt)
            ):
                return True
            prev = _preceding_sibling(node, parents)
            if prev is not None and any(
                sub is acquire for sub in ast.walk(prev)
            ):
                return True
        return False


#: dotted names of calls that block on I/O or another thread
_BLOCKING_DOTTED = frozenset(
    {
        "time.sleep", "sleep",
        "urlopen", "urllib.request.urlopen", "request.urlopen",
        "socket.create_connection", "create_connection",
        "subprocess.run", "subprocess.call", "subprocess.check_call",
        "subprocess.check_output",
    }
)

_REQUESTS_VERBS = frozenset(
    {"get", "post", "put", "patch", "delete", "head", "options", "request"}
)


def _is_blocking_call(node: ast.Call) -> str:
    """A human-readable name when ``node`` is a blocking call; ""
    otherwise."""
    dn = dotted_name(node.func)
    name = call_name(node)
    if dn in _BLOCKING_DOTTED:
        return dn
    if dn.startswith("requests.") and name in _REQUESTS_VERBS:
        return dn
    if "fsync" in name or "fdatasync" in name:
        return dn or name
    if isinstance(node.func, ast.Attribute):
        if name == "result":  # Future.result() — waits on another thread
            return f"{dotted_name(node.func.value) or '<expr>'}.result"
        if name == "join" and not node.args and not node.keywords:
            # thread.join(); str.join always takes an argument
            return f"{dotted_name(node.func.value) or '<expr>'}.join"
    return ""


class BlockingUnderLock(Rule):
    """A blocking call made while holding a lock convoys every thread
    that needs the lock behind the I/O: a slow peer or disk turns a
    microsecond critical section into a seconds-long global stall (and,
    for scrape-path locks, freezes ``/metrics`` with it)."""

    id = "conc-blocking-under-lock"
    severity = "error"
    short = (
        "blocking call (sleep/HTTP/fsync/result/join/subprocess) while "
        "holding a lock"
    )
    motivation = (
        "rollout/metadata persistence and replica apply paths hold "
        "locks that the serving and scrape threads also need; one "
        "blocking call under them stalls every request in the process"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # cheap bail: no known locks anywhere in this file
        if not ctx.module_locks and not any(
            cs.lock_attrs for cs in ctx.classes
        ):
            return
        parents = _parent_map(ctx)

        def holds(w: ast.With) -> Set[str]:
            got: Set[str] = set()
            for item in w.items:
                expr = item.context_expr
                attr = _self_attr(expr)
                if attr:
                    cs = _class_scope_of(w, ctx, parents)
                    if cs is not None and cs.lock_attrs.get(attr) in (
                        "lock", "rlock", "condition"
                    ):
                        got.add(f"self.{attr}")
                elif isinstance(expr, ast.Name) and ctx.module_locks.get(
                    expr.id
                ) in ("lock", "rlock", "condition"):
                    got.add(expr.id)
            return got

        for node, held in _iter_scope_with_lockstate(ctx.tree, holds):
            if not held or not isinstance(node, ast.Call):
                continue
            shown = _is_blocking_call(node)
            if shown:
                locks = ", ".join(sorted(held))
                yield self.finding(
                    ctx,
                    node,
                    f"{shown}(...) while holding {locks}: every thread "
                    "needing the lock waits out this call — move the "
                    "blocking work outside the critical section (snapshot "
                    "state under the lock, do I/O after).",
                )


class LockOrder(Rule):
    """Two locks taken in opposite nesting orders in the same file: one
    thread holding A waiting for B while another holds B waiting for A
    is a deadlock that needs exactly one bad interleaving."""

    id = "conc-lock-order"
    severity = "error"
    short = (
        "inconsistent multi-lock acquisition order (A→B here, B→A "
        "elsewhere): deadlock"
    )
    motivation = (
        "the rollout manager nests the server deploy lock inside its "
        "own; the moment any code path nests them the other way the "
        "query server deadlocks under load — pin one global order"
    )

    _LOCKISH = ("lock", "mutex", "cond", "sem")

    def _lock_name(self, expr: ast.AST, ctx: FileContext) -> str:
        dn = dotted_name(expr)
        if not dn:
            return ""
        if isinstance(expr, ast.Name):
            if ctx.module_locks.get(dn) in ("lock", "rlock", "condition"):
                return dn
            return ""
        tail = dn.rsplit(".", 1)[-1].lower()
        if any(tok in tail for tok in self._LOCKISH):
            return dn
        return ""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # cheap bail: the pair analysis only matters where with-blocks
        # on lock-looking names exist at all
        lowered = ctx.source.lower()
        if "with " not in lowered or not any(
            tok in lowered for tok in self._LOCKISH
        ):
            return
        #: ordered pair -> first witnessing inner `with` node
        pairs: Dict[Tuple[str, str], ast.AST] = {}

        def visit(node: ast.AST, held: List[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                     ast.ClassDef),
                ):
                    visit(child, [])
                    continue
                now = held
                if isinstance(child, ast.With):
                    names = [
                        n
                        for item in child.items
                        for n in [self._lock_name(item.context_expr, ctx)]
                        if n
                    ]
                    if names:
                        now = held + names
                        # `with A, B:` acquires left to right, so the
                        # items of ONE with statement order just like
                        # nested withs do
                        for i, inner in enumerate(names):
                            for outer in held + names[:i]:
                                if outer != inner:
                                    pairs.setdefault(
                                        (outer, inner), child
                                    )
                visit(child, now)

        visit(ctx.tree, [])
        reported: Set[frozenset] = set()
        for (outer, inner), node in sorted(
            pairs.items(), key=lambda kv: kv[1].lineno
        ):
            if (inner, outer) not in pairs:
                continue
            key = frozenset((outer, inner))
            if key in reported:
                continue
            reported.add(key)
            other = pairs[(inner, outer)]
            # report at the LATER occurrence: the first nesting in file
            # order establishes the convention, the reversed one breaks it
            first, second = sorted((node, other), key=lambda n: n.lineno)
            yield self.finding(
                ctx,
                second,
                f"locks {outer!r} and {inner!r} are nested in both "
                f"orders in this file (the other order is at line "
                f"{first.lineno}): two threads taking them oppositely "
                "deadlock — pin one acquisition order.",
            )


class ModuleMutable(Rule):
    """A module-level mutable registry mutated inside a function without
    a module-level lock held: import-time registries are fine, but a
    request-time mutation races every server thread that reads them."""

    id = "conc-module-mutable"
    severity = "error"
    short = (
        "module-level dict/list/set mutated at call time without a "
        "module-level lock held"
    )
    motivation = (
        "the breaker/seq-token registries in storage/remote.py get "
        "this right (one module lock around every mutation); a new "
        "registry that skips the lock corrupts itself under the "
        "threaded servers"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.module_mutables:
            return
        guards = {
            name
            for name, kind in ctx.module_locks.items()
            if kind in ("lock", "rlock", "condition")
        }

        def holds(w: ast.With) -> Set[str]:
            return {
                item.context_expr.id
                for item in w.items
                if isinstance(item.context_expr, ast.Name)
                and item.context_expr.id in guards
            }

        # the scope iterator descends into nested defs (restarting lock
        # state), and this loop visits nested defs directly too — dedupe
        # by node so a mutation inside `def outer(): def inner(): ...`
        # is reported once, not once per enclosing function
        reported: Set[int] = set()
        for func in ast.walk(ctx.tree):
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for node, held in _iter_scope_with_lockstate(func, holds):
                if held or id(node) in reported:
                    continue
                name = self._mutated_module_name(node, ctx)
                if name:
                    reported.add(id(node))
                    yield self.finding(
                        ctx,
                        node,
                        f"module-level {name!r} mutated at call time "
                        "without a module lock held: concurrent server "
                        "threads race the registry — guard mutations "
                        "with one module-level threading.Lock.",
                    )

    @staticmethod
    def _mutated_module_name(node: ast.AST, ctx: FileContext) -> str:
        def module_name(expr: ast.AST) -> str:
            if isinstance(expr, ast.Subscript):
                expr = expr.value
            if isinstance(expr, ast.Name) and expr.id in ctx.module_mutables:
                return expr.id
            return ""

        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    name = module_name(t)
                    if name:
                        return name
        elif isinstance(node, ast.AugAssign) and isinstance(
            node.target, ast.Subscript
        ):
            return module_name(node.target)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    name = module_name(t)
                    if name:
                        return name
        elif isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            from .engine import MUTATOR_METHODS

            if node.func.attr in MUTATOR_METHODS:
                base = node.func.value
                if isinstance(base, ast.Name) and \
                        base.id in ctx.module_mutables:
                    return base.id
        return ""


#: functions that read ambient per-request context (deadline/trace)
_AMBIENT_GETTERS = frozenset({"current_context", "current_deadline"})


class ContextvarThreadHop(Rule):
    """Contextvars do not cross thread boundaries: a thread-entry
    function reading an ambient contextvar gets the worker's empty
    context, silently dropping the request's deadline/trace. Capture the
    value at submit time and pass it explicitly — the discipline
    ``obs/trace.py`` and ``utils/resilience.py`` document and the PR-4
    batcher/feedback paths implement."""

    id = "conc-contextvar-thread-hop"
    severity = "error"
    short = (
        "ambient contextvar read (current_context()/<var>.get()) "
        "inside a cross-thread entry function"
    )
    motivation = (
        "the PR-4 trace plane lost spans exactly this way until every "
        "thread hop captured its SpanContext at submit time; the rule "
        "pins that discipline for future pools"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # cheap bail: no ambient getters or contextvars in this file
        if not ctx.module_contextvars and not any(
            getter in ctx.source for getter in _AMBIENT_GETTERS
        ):
            return
        parents = _parent_map(ctx)
        for entry, how in thread_entries(ctx, parents):
            for node in ast.walk(entry):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name in _AMBIENT_GETTERS:
                    yield self.finding(
                        ctx,
                        node,
                        f"{name}() inside a {how} reads the worker "
                        "thread's empty context — capture the value "
                        "before the thread hop and pass it explicitly.",
                    )
                elif (
                    name == "get"
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in ctx.module_contextvars
                ):
                    yield self.finding(
                        ctx,
                        node,
                        f"contextvar {node.func.value.id!r}.get() inside "
                        f"a {how}: contextvars do not follow thread "
                        "hops — capture at submit time and pass "
                        "explicitly.",
                    )


RULES: List[Rule] = [
    UnguardedAttr(),
    AcquireNoWith(),
    BlockingUnderLock(),
    LockOrder(),
    ModuleMutable(),
    ContextvarThreadHop(),
]
