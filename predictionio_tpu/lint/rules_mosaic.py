"""Family A — Mosaic/Pallas hygiene rules.

These rules run on functions identified as Pallas kernels (passed to
``pl.pallas_call``, plus module helpers they call — see
``engine._collect_kernels``) and on block-shape literals anywhere in a
file. Each rule encodes one bug class the round-5 deviceless AOT sweep
hit on real kernels (commit 093d7d2, ``ROUND5_NOTES.md``), so the
messages cite the incident; ``docs/lint.md`` carries the full catalog.

Naming conventions the detectors lean on (this codebase's idiom, stated
in docs/lint.md): kernel ref parameters end in ``_ref``; VMEM scratch
operands use other names (``a_s``, ``gbuf``) and are exempt from the
per-row-read heuristic because a scratch row read is not a DMA.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from .engine import (
    FileContext,
    Finding,
    Rule,
    call_name,
    dotted_name,
    index_elements,
    is_none_constant,
    subscript_base_name,
)

#: TPU tiling: lane (last) dim granularity and sublane (second-to-last)
#: granularity for f32. Rules use the f32 floor — stricter dtypes (bf16
#: sublane 16, int8 32) only tighten it, and the repo's kernels are f32
#: at the tile boundary.
LANE = 128
SUBLANE = 8


def _is_pl_ds(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and (
        dotted_name(node.func) in ("pl.ds", "pltpu.ds")
        or call_name(node) == "ds"
    )


def _fori_body_defs(func: ast.FunctionDef) -> List[ast.FunctionDef]:
    """FunctionDefs used as ``fori_loop``/``while_loop`` bodies anywhere
    inside ``func`` (nested defs included)."""
    defs = {
        n.name: n
        for n in ast.walk(func)
        if isinstance(n, ast.FunctionDef) and n is not func
    }
    out = []
    for node in ast.walk(func):
        if not isinstance(node, ast.Call):
            continue
        if call_name(node) not in ("fori_loop", "while_loop"):
            continue
        for arg in node.args:
            if isinstance(arg, ast.Name) and arg.id in defs:
                out.append(defs[arg.id])
    return out


class UnalignedLaneSlice(Rule):
    """The 093d7d2 bug: the exclusion top-k sliced its ``[B, E]`` buffer
    at 16-lane offsets, which Mosaic rejects outright; the fused-gather
    kernel's 1×56 row copies failed the same way. A ``pl.ds`` in the
    lane (last) position of a kernel ref subscript must be provably
    128-aligned in both offset and size."""

    id = "mosaic-unaligned-lane-slice"
    severity = "error"
    short = (
        "lane-dim pl.ds slice whose offset/size is not provably a "
        "multiple of 128"
    )
    motivation = (
        "round 5: exclusion top-k's 16-lane slices did not lower; "
        "gramian_fused's 1x56 row DMAs did not lower (commit 093d7d2)"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for kernel in ctx.kernels:
            smem = ctx.kernel_smem_params(kernel)
            for node in ast.walk(kernel):
                if not isinstance(node, ast.Subscript):
                    continue
                base = subscript_base_name(node)
                if not base.endswith("_ref") or base in smem:
                    continue
                elts = index_elements(node)
                if len(elts) < 2 or not _is_pl_ds(elts[-1]):
                    # a sole index is the sublane/leading dim (always
                    # lowerable); only the trailing position rides lanes
                    continue
                ds = elts[-1]
                if len(ds.args) < 2:
                    continue
                offset, size = ds.args[0], ds.args[1]
                bad: List[str] = []
                if not ctx.provably_multiple(offset, LANE):
                    bad.append("offset")
                if not ctx.provably_multiple(size, LANE):
                    bad.append("size")
                if bad:
                    yield self.finding(
                        ctx,
                        ds,
                        f"lane-dim slice of {subscript_base_name(node)!r} "
                        f"with {' and '.join(bad)} not provably a multiple "
                        f"of {LANE}: Mosaic rejects unaligned lane slices "
                        "(round-5 exclusion top-k bug). Restructure so the "
                        "lane offset/size are 128-aligned (e.g. transpose "
                        "the buffer and read leading-dim rows).",
                    )


class BlockSpecTiling(Rule):
    """Block shapes feed the Mosaic tiling directly: a VMEM block whose
    last dim is not a multiple of 128 (or second-to-last not a multiple
    of 8) either fails to lower or pays relayout copies. Applies to
    ``pl.BlockSpec`` shape tuples and ``pltpu.VMEM`` scratch shapes with
    statically resolvable dims; SMEM blocks are exempt (scalar memory
    has no lane tiling)."""

    id = "mosaic-blockspec-tiling"
    severity = "error"
    short = (
        "BlockSpec/VMEM block shape with last dim not %128 or "
        "second-to-last not %8"
    )
    motivation = (
        "same tiling contract the round-5 AOT sweep enforced; the "
        "streaming top-k pads queries to 8x128 for exactly this reason"
    )

    def _shape_findings(
        self, ctx: FileContext, call: ast.Call, shape: ast.Tuple,
        what: str,
    ) -> Iterator[Finding]:
        dims = [ctx.const_int(e) for e in shape.elts]
        if len(dims) >= 1 and dims[-1] is not None and dims[-1] % LANE:
            yield self.finding(
                ctx,
                call,
                f"{what} last (lane) dim {dims[-1]} is not a multiple of "
                f"{LANE}; the block will not tile onto the VPU/MXU "
                "lanes — pad the array and mask instead.",
            )
        if len(dims) >= 2 and dims[-2] is not None and dims[-2] % SUBLANE:
            yield self.finding(
                ctx,
                call,
                f"{what} second-to-last (sublane) dim {dims[-2]} is not a "
                f"multiple of {SUBLANE} (f32 tiling); pad to the sublane "
                "granule.",
            )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name == "BlockSpec":
                memory_space = next(
                    (
                        kw.value
                        for kw in node.keywords
                        if kw.arg == "memory_space"
                    ),
                    None,
                )
                if memory_space is not None and dotted_name(
                    memory_space
                ).rsplit(".", 1)[-1] in ("SMEM", "ANY", "HBM"):
                    continue
                if node.args and isinstance(node.args[0], ast.Tuple):
                    yield from self._shape_findings(
                        ctx, node, node.args[0], "BlockSpec block shape"
                    )
            elif name == "VMEM" and dotted_name(node.func).startswith(
                ("pltpu.", "tpu.")
            ):
                if node.args and isinstance(node.args[0], ast.Tuple):
                    yield from self._shape_findings(
                        ctx, node, node.args[0], "VMEM scratch shape"
                    )


class Rank3BroadcastCompare(Rule):
    """The second half of the 093d7d2 bug: widening the exclusion compare
    to an aligned ``[B, T, C]`` rank-3 broadcast made Mosaic compile
    pathologically (aborted after 15 minutes). Inside kernels, compares
    must stay rank ≤ 2 — restructure as a loop of 2-D compares."""

    id = "mosaic-rank3-compare"
    severity = "error"
    short = "comparison broadcasting to rank >= 3 inside a kernel"
    motivation = (
        "round 5: the [B, T, C] exclusion compare compiled for 15+ "
        "minutes before being aborted (commit 093d7d2)"
    )

    @staticmethod
    def _apparent_rank(node: ast.AST) -> Optional[int]:
        """Result rank of a subscript that uses ``None`` (newaxis)
        expansion; None when not statically apparent."""
        if not isinstance(node, ast.Subscript):
            return None
        elts = index_elements(node)
        if not any(is_none_constant(e) for e in elts):
            return None
        # every element is a dim of the result except scalar indices;
        # slices keep a dim, None adds one
        rank = 0
        for e in elts:
            if isinstance(e, ast.Slice) or is_none_constant(e):
                rank += 1
        return rank

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for kernel in ctx.kernels:
            for node in ast.walk(kernel):
                if not isinstance(node, ast.Compare):
                    continue
                operands = [node.left, *node.comparators]
                for op in operands:
                    rank = self._apparent_rank(op)
                    if rank is not None and rank >= 3:
                        yield self.finding(
                            ctx,
                            node,
                            f"comparison operand broadcast to rank {rank} "
                            "inside a kernel: Mosaic compiles rank-3 "
                            "broadcast compares pathologically (round-5 "
                            "exclusion bug — 15 min compile). Loop over "
                            "one dim with 2-D compares instead.",
                        )
                        break


class PerRowDMA(Rule):
    """One DMA (or one ref row read) per loop iteration moves data at
    well below the 128-lane floor and serializes on issue rate — the
    known ``gramian_fused`` weakness (PERF.md): its per-row gather is
    flag-gated until a hardware A/B prices the DMA-issue cost. Flags
    (a) ``make_async_copy`` with a size-1 sublane slice inside a loop
    body, and (b) single-row ``*_ref[i]`` reads per iteration."""

    id = "mosaic-per-row-dma"
    severity = "warning"
    short = (
        "per-row DMA or single-row ref read inside a loop body "
        "(below the 128-lane floor)"
    )
    motivation = (
        "gramian_fused's per-row gather DMAs (PERF.md round-3 weakness; "
        "round-5 fixed their alignment but the issue-rate risk stands) "
        "and the exclusion top-k's sequential E-step (ADVICE r5)"
    )

    @staticmethod
    def _has_unit_ds(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if _is_pl_ds(sub) and len(sub.args) >= 2:
                size = sub.args[1]
                if isinstance(size, ast.Constant) and size.value == 1:
                    return True
        return False

    def _loop_bodies(
        self, func: ast.FunctionDef
    ) -> List[Tuple[ast.AST, str]]:
        bodies: List[Tuple[ast.AST, str]] = []
        for body_def in _fori_body_defs(func):
            bodies.append((body_def, "fori_loop body"))
        for node in ast.walk(func):
            if isinstance(node, (ast.For, ast.While)):
                bodies.append((node, "Python loop body"))
        return bodies

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for kernel in ctx.kernels:
            smem = ctx.kernel_smem_params(kernel)
            seen: Set[int] = set()
            for body, kind in self._loop_bodies(kernel):
                for node in ast.walk(body):
                    if id(node) in seen:
                        continue
                    if isinstance(node, ast.Call) and call_name(node) in (
                        "make_async_copy", "async_copy",
                    ):
                        if self._has_unit_ds(node):
                            seen.add(id(node))
                            yield self.finding(
                                ctx,
                                node,
                                f"single-row async copy per {kind} "
                                "iteration: each DMA moves one sublane "
                                "row (the gramian_fused per-row gather "
                                "pattern) — batch rows into >= 8-sublane "
                                "tiles or accept the DMA-issue-rate risk "
                                "explicitly.",
                            )
                    elif isinstance(node, ast.Subscript) and isinstance(
                        node.ctx, ast.Load
                    ):
                        base = subscript_base_name(node)
                        elts = index_elements(node)
                        if (
                            base.endswith("_ref")
                            and base not in smem
                            and len(elts) == 1
                            and not isinstance(elts[0], ast.Slice)
                            and not _is_pl_ds(elts[0])
                            and not is_none_constant(elts[0])
                            and not isinstance(elts[0], ast.Constant)
                        ):
                            seen.add(id(node))
                            yield self.finding(
                                ctx,
                                node,
                                f"one row of {base!r} read per {kind} "
                                "iteration: sequential sub-128-lane "
                                "traffic (the exclusion top-k E-step "
                                "shape) — fine only when the trip count "
                                "is small and bounded.",
                            )


class UnboundedForiTrip(Rule):
    """A ``fori_loop`` whose trip count is derived from a runtime array
    dimension recompiles (and re-lowers) per shape and can grow without
    bound with the data; kernels should loop over static tile counts and
    let the grid absorb the data-scaled dim."""

    id = "mosaic-unbounded-fori"
    severity = "warning"
    short = "fori_loop trip count derived from a runtime array dim"
    motivation = (
        "the exclusion E-step's trip count scales with the blacklist "
        "width; ADVICE r5 flagged the widest widths as unmeasured — "
        "shape-derived trip counts make that scaling invisible"
    )

    @staticmethod
    def _shape_derived_names(func: ast.FunctionDef) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(func):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Attribute) and sub.attr == "shape":
                        names.add(node.targets[0].id)
                        break
        return names

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for kernel in ctx.kernels:
            shape_names = self._shape_derived_names(kernel)
            for node in ast.walk(kernel):
                if not isinstance(node, ast.Call) or call_name(node) != \
                        "fori_loop":
                    continue
                if len(node.args) < 2:
                    continue
                hi = node.args[1]
                derived = False
                for sub in ast.walk(hi):
                    if isinstance(sub, ast.Attribute) and sub.attr == "shape":
                        derived = True
                    if isinstance(sub, ast.Name) and sub.id in shape_names:
                        derived = True
                if derived:
                    yield self.finding(
                        ctx,
                        node,
                        "fori_loop trip count derives from a runtime array "
                        "dim: the loop re-lowers per shape and scales "
                        "unboundedly with the data — use a static tile "
                        "count and ride the grid over the data dim.",
                    )


class Bf16AccumWithoutF32(Rule):
    """The round-12 bf16-gather default's safety contract: a bf16 input
    halves gather bytes and doubles MXU rate ONLY because accumulation
    stays f32 via ``preferred_element_type=jnp.float32`` — a
    ``dot``/``matmul``/``einsum``/``dot_general`` that drops the kwarg
    accumulates at bf16, and the resulting precision slide surfaces as
    an RMSE drift the bench gate catches only after the fact. Applied
    package-wide (the einsum sites live OUTSIDE kernels — the
    ``als.py`` gather build is the clean exemplar). Taint is tracked
    per top-level scope: a name assigned from a bf16 cast (or a
    conditional that may produce one, the ``gdt = jnp.bfloat16 if ...``
    idiom) taints everything derived from it; an explicit
    ``.astype(jnp.float32)`` clears it."""

    id = "mosaic-bf16-accum"
    severity = "error"
    short = (
        "bf16-cast operand feeds dot/matmul/einsum without "
        "preferred_element_type forcing f32 accumulation"
    )
    motivation = (
        "the round-12 gather_dtype='bf16' lever (ALSConfig): its "
        "equivalence proof (bench bf16 RMSE gate) holds only while "
        "every contraction over bf16 operands pins f32 accumulation — "
        "als.py's _system_explicit/_system_implicit einsums are the "
        "clean exemplar"
    )

    #: contraction calls whose accumulator dtype follows the operand
    #: dtype unless preferred_element_type overrides it
    _CONTRACTIONS = ("einsum", "dot", "matmul", "dot_general", "tensordot")

    @staticmethod
    def _mentions_bf16(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and sub.attr == "bfloat16":
                return True
            if isinstance(sub, ast.Name) and sub.id == "bfloat16":
                return True
            if isinstance(sub, ast.Constant) and sub.value == "bfloat16":
                return True
        return False

    @staticmethod
    def _mentions_f32(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and sub.attr in (
                "float32", "float64",
            ):
                return True
            if isinstance(sub, ast.Constant) and sub.value in (
                "float32", "float64",
            ):
                return True
        return False

    def _value_tainted(self, value: ast.AST, tainted: Set[str]) -> bool:
        """Does ``value`` (an RHS or call argument) carry possibly-bf16
        data? A pure f32 upcast (``x.astype(jnp.float32)``) clears the
        taint — including NESTED inside an expression
        (``g.astype(jnp.float32) * w`` is clean); ``x.astype(gdt)``
        with a tainted/bf16 dtype argument keeps it."""
        # names under a clearing f32 upcast are exempt from the walk
        cleared: Set[int] = set()
        for sub in ast.walk(value):
            if not (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "astype"
                and len(sub.args) == 1
            ):
                continue
            dtype_arg = sub.args[0]
            if self._mentions_bf16(dtype_arg):
                return True  # an explicit bf16 cast anywhere taints
            if any(
                isinstance(n, ast.Name) and n.id in tainted
                for n in ast.walk(dtype_arg)
            ):
                # .astype(gdt) / .astype(g.dtype): dtype follows a
                # possibly-bf16 source — NOT a clearing cast
                continue
            if self._mentions_f32(dtype_arg):
                for n in ast.walk(sub.func.value):
                    cleared.add(id(n))
        if self._mentions_bf16(value):
            return True
        return any(
            isinstance(sub, ast.Name)
            and sub.id in tainted
            and id(sub) not in cleared
            for sub in ast.walk(value)
        )

    @staticmethod
    def _iter_assigns(root: ast.AST) -> Iterator[Tuple[str, ast.AST]]:
        """(name, value) pairs for every name-binding assignment under
        ``root`` — plain and annotated assigns, plus tuple unpacking
        (``g1, g2 = a.astype(gdt), b.astype(gdt)`` pairs element-wise;
        unpacking an opaque RHS taints every bound name with it)."""
        for node in ast.walk(root):
            if (
                isinstance(node, ast.AnnAssign)
                and isinstance(node.target, ast.Name)
                and node.value is not None
            ):
                yield node.target.id, node.value
                continue
            if not (
                isinstance(node, ast.Assign) and len(node.targets) == 1
            ):
                continue
            target = node.targets[0]
            if isinstance(target, ast.Name):
                yield target.id, node.value
            elif isinstance(target, ast.Tuple) and all(
                isinstance(elt, ast.Name) for elt in target.elts
            ):
                if isinstance(node.value, ast.Tuple) and len(
                    node.value.elts
                ) == len(target.elts):
                    for elt, value in zip(target.elts, node.value.elts):
                        yield elt.id, value
                else:
                    for elt in target.elts:
                        yield elt.id, node.value

    @staticmethod
    def _scopes(ctx: FileContext) -> List[ast.AST]:
        """Top-level analysis units: module + each outermost function
        (nested defs analyzed WITH their parent so closure-captured
        casts — the ``_solve_side_traced`` idiom — stay visible)."""
        out: List[ast.AST] = [ctx.tree]
        stack: List[ast.AST] = [ctx.tree]
        while stack:
            node = stack.pop()
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    out.append(child)
                elif isinstance(child, (ast.ClassDef, ast.Module)):
                    stack.append(child)
        return out

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if "bfloat16" not in ctx.source:
            return  # cheap source-text bail (tier-1 sweep budget)
        module_seed: Set[str] = set()
        for stmt in ctx.tree.body:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                for name, value in self._iter_assigns(stmt):
                    if self._mentions_bf16(value):
                        module_seed.add(name)
        reported: Set[int] = set()
        for scope in self._scopes(ctx):
            if isinstance(scope, ast.Module):
                # module-level statements only: functions are their own
                # units (a name in one function must not taint the same
                # name in another), and class methods arrive via _scopes
                body = [
                    stmt
                    for stmt in scope.body
                    if not isinstance(
                        stmt,
                        (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef),
                    )
                ]
            else:
                body = [scope]
            assigns: List[Tuple[str, ast.AST]] = []
            for root in body:
                assigns.extend(self._iter_assigns(root))
            tainted = set(module_seed)
            changed = True
            while changed:  # tiny fixpoint; assignment count bounds it
                changed = False
                for name, value in assigns:
                    if name in tainted:
                        continue
                    if self._value_tainted(value, tainted):
                        tainted.add(name)
                        changed = True
            if not tainted:
                continue
            for root in body:
                for node in ast.walk(root):
                    if id(node) in reported:
                        continue
                    if isinstance(node, ast.BinOp) and isinstance(
                        node.op, ast.MatMult
                    ):
                        # the @ operator CANNOT carry
                        # preferred_element_type at all — with a bf16
                        # operand it always accumulates at bf16
                        if self._value_tainted(
                            node.left, tainted
                        ) or self._value_tainted(node.right, tainted):
                            reported.add(id(node))
                            yield self.finding(
                                ctx,
                                node,
                                "`@` over a possibly-bf16 operand: the "
                                "operator form cannot pin an "
                                "accumulator dtype — use jnp.einsum/"
                                "jax.lax.dot_general with "
                                "preferred_element_type=jnp.float32, "
                                "or upcast the operand explicitly.",
                            )
                        continue
                    if not isinstance(node, ast.Call):
                        continue
                    if call_name(node) not in self._CONTRACTIONS:
                        continue
                    if any(
                        kw.arg == "preferred_element_type"
                        for kw in node.keywords
                    ):
                        continue
                    if any(
                        self._value_tainted(arg, tainted)
                        for arg in node.args
                        if not isinstance(arg, ast.Constant)
                    ):
                        reported.add(id(node))
                        yield self.finding(
                            ctx,
                            node,
                            f"{call_name(node)} over a possibly-bf16 "
                            "operand without preferred_element_type: the "
                            "MXU will accumulate at bf16 and the "
                            "precision loss lands in the result — pin "
                            "preferred_element_type=jnp.float32 (the "
                            "als.py normal-equation einsums are the "
                            "exemplar) or upcast the operand explicitly.",
                        )


RULES = [
    UnalignedLaneSlice(),
    BlockSpecTiling(),
    Rank3BroadcastCompare(),
    PerRowDMA(),
    UnboundedForiTrip(),
    Bf16AccumWithoutF32(),
]
