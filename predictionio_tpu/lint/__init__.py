"""``pio lint``: TPU-hygiene static analysis for the whole package.

Round 5's deviceless AOT sweep (``tests/test_mosaic_aot.py``, commit
093d7d2) found three real Mosaic lowering bugs that interpret-mode tests
could never see — unaligned lane slices, a rank-3 broadcast compare that
compiled pathologically, and sub-128-lane row DMAs — and each cost a
full compile-debug cycle. The bug classes are mechanical, so this
package catches them at AST level, before XLA/Mosaic ever runs: the
"catch it in the graph, not on the device" discipline.

Seven rule families (see ``docs/lint.md`` for the full catalog):

- **Family A — Mosaic/Pallas hygiene** (``rules_mosaic``): applied to
  functions passed to ``pl.pallas_call`` (plus helpers they call) and to
  block-shape literals anywhere. Rule ids ``mosaic-*``.
- **Family B — jit-boundary hygiene** (``rules_jit``): applied
  package-wide. Rule ids ``jit-*``.
- **Family C — robustness hygiene** (``rules_robust``): applied
  package-wide; guards the ISSUE-2 resilience discipline (timeouts on
  every network call, jittered retries). Rule ids ``robust-*``.
- **Family D — observability hygiene** (``rules_obs``): applied
  package-wide; guards the ISSUE-4 metric-cardinality discipline.
  Rule ids ``obs-*``.
- **Family E — concurrency / lock discipline** (``rules_conc``,
  ISSUE 6): applied package-wide; per-class inference of lock-guarded
  state and cross-thread entry points over the threaded control plane
  (shadow pools, tailers, scrape callbacks). Rule ids ``conc-*``.
- **Family F — SPMD / multi-host consistency** (``rules_spmd``,
  ISSUE 6): applied package-wide; guards the distributed-training arc
  against host-divergent collectives, axis-name/spec drift, unordered
  operand construction, and host-dependent RNG. Rule ids ``spmd-*``.
- **Family G — cross-file flow rules** (``rules_flow`` over the
  ``packagectx`` call graph, ISSUE 16): blocking helpers invoked under
  a held lock, deadlines dropped at module boundaries, started threads
  with no reachable stop story, and the call-graph upgrade of
  ``spmd-collective-missing-axis`` that judges ``*args``/``**kwargs``
  forwarding. One-level resolution by contract; what does not resolve
  is not judged. Rule ids ``flow-*``.

The engine is incremental: full default-rule sweeps keep a result cache
keyed by content hash, import-closure hash (for ``flow-*``) and rules
signature, and the per-file pass runs in worker processes — both speed
levers only, never able to change a verdict (``docs/lint.md#cache``).

Suppression: ``# pio: lint-ok[rule-id] reason`` on the finding's line or
as a comment-only line directly above. The reason is mandatory — a bare
suppression is itself a finding (``lint-suppression-missing-reason``),
and one whose rule ran but matched nothing is stale
(``lint-unused-suppression``) — so the self-lint gate in
``tests/test_lint.py`` enforces that every intentional exception in the
tree carries its one-line justification and stays live.
"""

from .engine import (
    FileContext,
    Finding,
    LintResult,
    Rule,
    all_rules,
    lint_file,
    lint_paths,
    render_json,
    render_text,
)

__all__ = [
    "FileContext",
    "Finding",
    "LintResult",
    "Rule",
    "all_rules",
    "lint_file",
    "lint_paths",
    "render_json",
    "render_text",
]
