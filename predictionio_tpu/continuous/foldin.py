"""ALS fold-in: incremental factor updates for new/changed rows.

The cheap step of the continuous-training loop (*ALX: Large Scale Matrix
Factorization on TPUs*, PAPERS.md): instead of re-running the full
alternating iteration over every row, solve **only the rows the fresh
delta touched** — each one an independent regularized least-squares
system against the *fixed* counterpart factor table, exactly the
per-row normal equations the full trainer builds
(:func:`~predictionio_tpu.ops.als._system_explicit`):

    A_u = Gᵀ G + λ n_u I,   b_u = Gᵀ r_u,   x_u = A_u⁻¹ b_u

Rows nobody touched keep their factors **bit-identical** — the no-op
guarantee the zero-delta test pins. New users/items get appended rows
(seeded like :func:`~predictionio_tpu.ops.als.init_factors`) and a
couple of restricted alternations (``fold_iterations``) resolve the
new-user-rated-new-item coupling.

Fold-in is an approximation: it holds every untouched row fixed, so its
quality degrades as the delta grows. :class:`FoldInPolicy` pins when the
approximation is no longer trustworthy — delta fraction, new-entity
fraction, or post-fold RMSE drift past policy limits escalates to a full
retrain (:data:`FULL_RETRAIN`). Everything here is host math + jittable
solves: CPU-testable, device-agnostic, no storage access.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.als import _cho_solve, _system_explicit, init_factors
from ..ops.scoring import pad_pow2

__all__ = [
    "FOLD_IN",
    "FULL_RETRAIN",
    "FoldInPolicy",
    "FoldInStats",
    "decide_mode",
    "fold_in_factors",
    "solve_rows",
]

#: mode verdicts of :func:`decide_mode`
FOLD_IN = "fold_in"
FULL_RETRAIN = "full_retrain"


@dataclasses.dataclass(frozen=True)
class FoldInPolicy:
    """When the incremental step is trustworthy (``docs/continuous.md``).

    Every threshold escalates to :data:`FULL_RETRAIN` when crossed —
    fold-in must never silently degrade model quality in the steady
    no-human loop."""

    #: delta events / total training events above which the "hold
    #: everything else fixed" approximation is no longer local
    max_delta_fraction: float = 0.2
    #: (new users + new items) / (known users + known items) above which
    #: the fixed counterpart tables no longer span the data
    max_new_entity_fraction: float = 0.2
    #: post-fold full-data RMSE may exceed the pre-fold RMSE by at most
    #: this *fraction* (relative drift); beyond it the fold is judged to
    #: have damaged the model and the controller escalates
    max_rmse_drift: float = 0.1
    #: restricted alternations over the changed rows (2 resolves the
    #: new-user × new-item coupling; 1 is pure one-shot fold-in)
    fold_iterations: int = 2
    #: widest per-row system staged at once; rows with more ratings than
    #: this are solved on their most recent ``max_row_width`` entries
    max_row_width: int = 2048

    def __post_init__(self):
        if self.fold_iterations < 1:
            raise ValueError(
                f"fold_iterations must be >= 1, got {self.fold_iterations}"
            )


def decide_mode(
    policy: FoldInPolicy,
    *,
    total_events: int,
    delta_events: int,
    known_entities: int,
    new_entities: int,
    fold_in_available: bool = True,
) -> Tuple[str, str]:
    """One (mode, reason) decision for a pending delta."""
    if not fold_in_available:
        return FULL_RETRAIN, "engine has no fold_in entry point"
    if total_events <= 0 or known_entities <= 0:
        return FULL_RETRAIN, "no trained baseline data to fold into"
    delta_frac = delta_events / max(1, total_events)
    if delta_frac > policy.max_delta_fraction:
        return FULL_RETRAIN, (
            f"delta fraction {delta_frac:.3f} exceeds "
            f"{policy.max_delta_fraction:.3f} "
            f"({delta_events}/{total_events} events)"
        )
    new_frac = new_entities / max(1, known_entities)
    if new_frac > policy.max_new_entity_fraction:
        return FULL_RETRAIN, (
            f"new-entity fraction {new_frac:.3f} exceeds "
            f"{policy.max_new_entity_fraction:.3f} "
            f"({new_entities} new / {known_entities} known)"
        )
    return FOLD_IN, (
        f"delta {delta_events}/{total_events} events, "
        f"{new_entities} new entities: within fold-in policy"
    )


@functools.partial(jax.jit, static_argnames=("rank",))
def solve_rows(
    counter: jax.Array,
    idx: jax.Array,
    val: jax.Array,
    mask: jax.Array,
    lam: jax.Array,
    rank: int,
) -> jax.Array:
    """Batched per-row regularized least squares against fixed counterpart
    factors: ``counter`` [N, R], ``idx``/``val``/``mask`` [B, K] → [B, R].

    The same normal equations as one half of a full ALS iteration
    (``ops/als.py``), jit-compiled per (B, K) shape — callers pad both to
    powers of two so the program set stays O(log²)."""
    a, b = _system_explicit(counter, idx, val, mask.astype(counter.dtype), lam, rank)
    return _cho_solve(a, b)


# jit boundary telemetry (docs/observability.md#profiling): fold-in runs
# inside the continuous controller's tick — a retrace storm here (e.g. a
# pow2-padding regression in _row_systems) silently eats the freshness
# budget; the counter makes it a /metrics fact instead
from ..obs.profile import default_telemetry as _default_telemetry

solve_rows = _default_telemetry().wrap("fold_in.solve_rows", solve_rows)


@dataclasses.dataclass
class FoldInStats:
    """What one fold did — the controller's policy/obs input."""

    folded_users: int
    folded_items: int
    new_users: int
    new_items: int
    rmse_before: float
    rmse_after: float

    @property
    def rmse_drift(self) -> float:
        """Relative full-data RMSE drift (positive = fold made it worse)."""
        if self.rmse_before <= 0.0:
            return 0.0
        return (self.rmse_after - self.rmse_before) / self.rmse_before

    def to_json(self) -> dict:
        return {
            "foldedUsers": self.folded_users,
            "foldedItems": self.folded_items,
            "newUsers": self.new_users,
            "newItems": self.new_items,
            "rmseBefore": round(self.rmse_before, 6),
            "rmseAfter": round(self.rmse_after, 6),
            "rmseDrift": round(self.rmse_drift, 6),
        }


def _row_systems(
    row_ids: np.ndarray,
    col_ids: np.ndarray,
    vals: np.ndarray,
    rows: np.ndarray,
    max_width: int,
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
    """Padded per-row systems for ``rows`` out of COO data keyed by
    ``row_ids``: returns (rows_kept, idx [B, K], val [B, K], mask [B, K])
    with B and K padded to powers of two, or None when no requested row
    has any rating (nothing to solve)."""
    order = np.argsort(row_ids, kind="stable")
    sorted_rows = row_ids[order]
    starts = np.searchsorted(sorted_rows, rows, side="left")
    ends = np.searchsorted(sorted_rows, rows, side="right")
    counts = ends - starts
    keep = counts > 0  # a row with zero ratings has a singular system:
    # leave its factors untouched instead of solving λ·0·I x = 0
    rows, starts, ends = rows[keep], starts[keep], ends[keep]
    if len(rows) == 0:
        return None
    counts = np.minimum(ends - starts, max_width)
    width = int(min(pad_pow2(int(counts.max()), lo=8), max_width))
    b_pad = pad_pow2(len(rows))
    idx = np.zeros((b_pad, width), dtype=np.int32)
    val = np.zeros((b_pad, width), dtype=np.float32)
    mask = np.zeros((b_pad, width), dtype=np.float32)
    for r in range(len(rows)):
        # keep the NEWEST `count` ratings when a row overflows the width
        # (the stable sort preserves arrival order within a row, so the
        # tail of its slice is the most recent feedback)
        sel = order[ends[r] - counts[r]: ends[r]]
        idx[r, : len(sel)] = col_ids[sel]
        val[r, : len(sel)] = vals[sel]
        mask[r, : len(sel)] = 1.0
    return rows, idx, val, mask


def fold_in_factors(
    user_factors: np.ndarray,
    item_factors: np.ndarray,
    users: np.ndarray,
    items: np.ndarray,
    ratings: np.ndarray,
    changed_users: Sequence[int],
    changed_items: Sequence[int],
    lambda_: float,
    policy: FoldInPolicy = FoldInPolicy(),
) -> Tuple[np.ndarray, np.ndarray, Dict[str, int]]:
    """Fold changed rows into copies of the factor tables.

    ``users``/``items``/``ratings`` are the FULL current training COO in
    the (already extended) index space of the factor tables —
    fold-in re-solves each changed row against **all** of its ratings
    (solving only a row's delta ratings would discard its history), but
    only the changed rows. Rows whose index ≥ the incoming table length
    are new: callers pass tables already extended (e.g. by
    :meth:`~predictionio_tpu.models.recommendation.ALSAlgorithm.fold_in`)
    with seeded rows for new entities.

    Returns ``(user_factors, item_factors, counts)`` — fresh arrays;
    untouched rows are byte-identical to the inputs.
    """
    rank = user_factors.shape[1]
    uf = np.array(user_factors, dtype=np.float32, copy=True)
    itf = np.array(item_factors, dtype=np.float32, copy=True)
    cu = np.asarray(sorted(set(int(u) for u in changed_users)), dtype=np.int32)
    ci = np.asarray(sorted(set(int(i) for i in changed_items)), dtype=np.int32)
    lam = jnp.float32(lambda_)
    counts = {"solved_users": 0, "solved_items": 0}
    for _ in range(policy.fold_iterations):
        if len(ci):
            sys_i = _row_systems(items, users, ratings, ci, policy.max_row_width)
            if sys_i is not None:
                rows, idx, val, mask = sys_i
                solved = np.asarray(
                    solve_rows(jnp.asarray(uf), jnp.asarray(idx),
                               jnp.asarray(val), jnp.asarray(mask), lam, rank)
                )
                itf[rows] = solved[: len(rows)]
                counts["solved_items"] = len(rows)
        if len(cu):
            sys_u = _row_systems(users, items, ratings, cu, policy.max_row_width)
            if sys_u is not None:
                rows, idx, val, mask = sys_u
                solved = np.asarray(
                    solve_rows(jnp.asarray(itf), jnp.asarray(idx),
                               jnp.asarray(val), jnp.asarray(mask), lam, rank)
                )
                uf[rows] = solved[: len(rows)]
                counts["solved_users"] = len(rows)
    return uf, itf, counts


def seeded_rows(n_new: int, rank: int, seed: int, offset: int) -> np.ndarray:
    """Initial factors for appended rows: the same distribution family as
    :func:`~predictionio_tpu.ops.als.init_factors`, keyed off the row
    offset so re-folding after more growth never re-mints earlier rows'
    seeds."""
    if n_new <= 0:
        return np.zeros((0, rank), dtype=np.float32)
    return np.asarray(init_factors(n_new, rank, seed + offset))


def extend_bimap_indexing(
    known: Dict[str, int], incoming_ids: Sequence[str]
) -> Tuple[Dict[str, int], int]:
    """Append unseen ids to a forward map in arrival order, preserving
    every existing index (the stable-index contract untouched factor rows
    rely on). Returns ``(combined_map, n_new)``."""
    combined = dict(known)
    n = len(combined)
    for key in incoming_ids:
        if key not in combined:
            combined[key] = n
            n += 1
    return combined, n - len(known)
