"""Continuous-learning plane: changefeed-driven fold-in training with
automatic rollout submission (``docs/continuous.md``).

Three cooperating parts close the loop the ROADMAP calls "continuous
learning from the feedback stream":

- :mod:`~predictionio_tpu.continuous.watcher` — tails the PR-3
  changefeed from a durably persisted cursor and accumulates a delta
  batch of fresh rating/feedback events;
- :mod:`~predictionio_tpu.continuous.foldin` — the ALX-style incremental
  step: solve only new/changed factor rows against fixed counterpart
  factors, with policy thresholds that escalate to a full retrain;
- :mod:`~predictionio_tpu.continuous.controller` — the policy state
  machine that turns deltas into candidate models and auto-submits them
  through the rollout plane's shadow→canary→live gates.
"""

from .controller import ContinuousConfig, ContinuousController
from .foldin import FOLD_IN, FULL_RETRAIN, FoldInPolicy, decide_mode
from .watcher import DeltaBatch, FeedGap, FeedWatcher, LocalFeed, RemoteFeed

__all__ = [
    "ContinuousConfig",
    "ContinuousController",
    "DeltaBatch",
    "FeedGap",
    "FeedWatcher",
    "FoldInPolicy",
    "FOLD_IN",
    "FULL_RETRAIN",
    "LocalFeed",
    "RemoteFeed",
    "decide_mode",
]
