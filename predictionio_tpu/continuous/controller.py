"""Continuous-training controller: delta → candidate → rollout, no human.

The policy state machine of the continuous-learning plane
(``docs/continuous.md``), the architecture of *Scalable Machine Learning
Training Infrastructure for Online Ads Recommendation and Auction
Scoring Modeling at Google* (PAPERS.md): ingestion → continuous train →
validated push in a steady loop.

One controller rides inside a
:class:`~predictionio_tpu.workflow.serving.QueryServer`:

1. **Watch** — :class:`~predictionio_tpu.continuous.watcher.FeedWatcher`
   tails the changefeed from its durable cursor; a cycle triggers when
   the pending delta reaches ``min_events`` or its oldest event exceeds
   ``max_staleness_s``.
2. **Train** — :func:`~predictionio_tpu.continuous.foldin.decide_mode`
   picks ALS fold-in (solve only changed rows) or a full retrain
   (delta/new-entity fraction past policy, post-fold RMSE drift, feed
   gap, or a quarantined previous fold). Either way the candidate goes
   through the existing train/persist path and lands as a COMPLETED
   engine instance.
3. **Score** — the candidate is replayed offline against the live
   baseline over the most recent variant-tagged ``pio_pr`` feedback
   events (PR 5); a candidate whose predictions diverge past
   ``max_offline_divergence`` is quarantined before it ever sees
   traffic.
4. **Submit & monitor** — the candidate auto-submits to
   :meth:`RolloutManager.start` and the controller watches the
   shadow→canary→live progression. A busy rollout backs off on the
   shared :class:`~predictionio_tpu.utils.resilience.RetryPolicy`
   schedule; a gate rollback quarantines the candidate, forces the next
   cycle to a full retrain, and starts a cooldown. Going LIVE commits
   the cursor and records end-to-end freshness (oldest folded event →
   model live).

Everything decision-shaped runs on injected clocks; the background
thread is just ``tick()`` on an interval. Restart resume: the durable
cursor plus ``continuous_state.json`` (in-flight candidate, quarantine
set) let a restarted server pick up exactly where it stopped — the
rollout itself resumes through the PR-5 plan record.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Mapping, Optional, Tuple

from ..storage.metadata import (
    ROLLOUT_ABORTED,
    ROLLOUT_LIVE,
    ROLLOUT_ROLLED_BACK,
)
from ..utils.durability import atomic_write_bytes
from ..utils.resilience import RetryPolicy
from .foldin import FOLD_IN, FULL_RETRAIN, FoldInPolicy, decide_mode
from ..obs.flight import record as flight_record
from .watcher import FeedGap, RemoteFeed, make_watcher

logger = logging.getLogger(__name__)

__all__ = ["ContinuousConfig", "ContinuousController", "STATE_NAME"]

STATE_NAME = "continuous_state.json"

#: controller states (status()["state"])
WATCHING = "WATCHING"
SUBMIT_PENDING = "SUBMIT_PENDING"
MONITORING = "MONITORING"
COOLDOWN = "COOLDOWN"
PAUSED = "PAUSED"


def _default_event_values() -> Dict[str, object]:
    # the recommendation template's rate/buy rules (workflow/infeed.py)
    return {"rate": "rating", "buy": 4.0}


@dataclasses.dataclass(frozen=True)
class ContinuousConfig:
    """Policy knobs of one continuous-learning loop
    (``docs/continuous.md#policy-knobs``)."""

    #: app whose feedback stream feeds the loop
    app_id: int = 1
    #: event name → value rule (property name or fixed float), the same
    #: shape the training infeed consumes
    event_values: Mapping[str, object] = dataclasses.field(
        default_factory=_default_event_values
    )
    #: storage primary to tail over ``GET /replicate/changes``; None =
    #: the caller passes an explicit feed object (in-process oplog)
    feed_url: Optional[str] = None
    #: cursor/state directory (default ``$PIO_FS_BASEDIR/continuous``)
    state_dir: Optional[str] = None
    #: delta size that triggers a training cycle
    min_events: int = 10
    #: trigger even below ``min_events`` once the oldest pending event is
    #: this stale (freshness floor)
    max_staleness_s: float = 300.0
    #: background tick cadence
    poll_interval_s: float = 1.0
    #: fold-vs-retrain escalation thresholds
    policy: FoldInPolicy = dataclasses.field(default_factory=FoldInPolicy)
    #: forwarded to ``RolloutManager.start``
    rollout_percent: Optional[float] = None
    rollout_gates: Optional[Mapping[str, object]] = None
    #: recent ``pio_pr`` feedback events replayed for offline scoring
    score_window: int = 200
    #: minimum scored samples before the offline gate can veto
    min_score_samples: int = 5
    #: mean candidate-vs-served-baseline divergence above which the
    #: candidate is quarantined without ever being submitted
    max_offline_divergence: float = 0.75
    #: cooldown after a rollback/quarantine before the next cycle
    quarantine_backoff_s: float = 300.0
    #: concurrent per-partition fold workers when the feed is partitioned
    #: and the engine folds per partition
    #: (docs/continuous.md#partitioned-folds)
    fold_workers: int = 2
    #: bound on how long a partitioned fold waits for straggler
    #: partitions; a partition past the deadline is skipped this cycle —
    #: its cursor stays put and its delta re-folds next cycle, so a slow
    #: partition never blocks another's commit. 0 = wait for every
    #: partition.
    fold_partition_timeout_s: float = 0.0
    #: checkpoint cadence for FULL retrains (docs/checkpoint.md): the
    #: retrain workflow's ``--checkpoint-every`` equivalent. The batch
    #: slug is stable ("continuous-retrain"), so a retrain killed
    #: mid-run — node preemption, controller restart — leaves committed
    #: checkpoints behind and the NEXT full retrain resumes from the
    #: latest valid one instead of starting over. None defers to the
    #: engine params / ``PIO_CKPT_EVERY`` tri-state; 0 forces off.
    retrain_checkpoint_every: Optional[int] = None
    #: start the background tick thread with the server
    autostart: bool = True


class ContinuousController:
    """One query server's continuous-learning loop (docs/continuous.md)."""

    def __init__(
        self,
        server,
        config: ContinuousConfig,
        feed=None,
        clock: Optional[Callable[[], float]] = None,
        wall: Callable[[], float] = time.time,
        retry_policy: Optional[RetryPolicy] = None,
    ):
        self.server = server
        self.config = config
        self.clock = clock or server.clock
        self.wall = wall
        self._retry = retry_policy or RetryPolicy(
            attempts=1, base_delay_s=1.0, max_delay_s=60.0
        )
        if feed is None:
            if not config.feed_url:
                raise ValueError(
                    "continuous learning needs a changefeed: pass feed_url "
                    "(a storage primary's URL) or an explicit feed object"
                )
            from ..storage.partition import partition_primaries

            # a partitioned URL (';'-separated sets,
            # docs/storage.md#partitioning) tails one changefeed per
            # partition primary, merged with independent durable
            # cursors by PartitionedFeedWatcher
            primaries = partition_primaries(config.feed_url)
            feed = (
                [RemoteFeed(u) for u in primaries]
                if len(primaries) > 1
                else RemoteFeed(primaries[0])
            )
        state_dir = config.state_dir
        if state_dir is None:
            from ..storage.registry import base_dir

            # the SERVER's storage env, not os.environ: a test/embedded
            # registry rooted elsewhere must keep its cursor there too
            reg_env = getattr(server.registry, "_env", None)
            state_dir = os.path.join(base_dir(reg_env), "continuous")
        self._state_dir = state_dir
        self._state_path = os.path.join(state_dir, STATE_NAME)
        self.watcher = make_watcher(
            feed, config.app_id, config.event_values, state_dir
        )
        # Feedback join (docs/observability.md#quality): every accepted
        # delta event is a user acting on an item — the quality monitor
        # records whether that item was in the user's last served list
        # (hit-rate + served-rank), the loop's real online-quality
        # number next to the offline divergence gate.
        self.watcher.on_event = self._observe_feedback
        # Health plane (docs/slo.md): the controller's tick and the feed
        # poll heartbeat the server's stall watchdog, and a tap failure
        # the watcher swallows is COUNTED, never just debug-logged.
        health = getattr(server, "health", None)
        self._watchdog = health.watchdog if health is not None else None
        self._tap_errors = server.metrics.counter(
            "pio_observer_errors_total",
            "Swallowed observer/monitor exceptions by site",
            labelnames=("site",),
        )
        self.watcher.on_event_error = lambda: self._tap_errors.inc(
            1, site="continuous.feedback"
        )
        if self._watchdog is not None:
            self.watcher.heartbeat = lambda: self._watchdog.beat(
                "continuous.feed"
            )
        self._lock = threading.Lock()
        self._ticking = False  # single-tick gate (flag, not a held lock:
        # a tick trains models — nothing may block behind it)
        self._paused = False
        self._force_full = False
        self._feed_gap = False  # a gap retrain must RESYNC (not commit)
        # the cursor at LIVE, or the gap re-fires on every later poll
        self._trigger = False
        self._candidate: Optional[dict] = None  # {"instanceId", "uptoSeq",
        # "oldestMs", "mode", "submitted", "createdS"}
        self._quarantined: List[str] = []
        self._cooldown_until = 0.0
        self._next_submit_s = 0.0
        self._submit_attempts = 0
        self._last_cycle: Optional[dict] = None
        self._last_freshness_s: Optional[float] = None
        self._last_error: Optional[str] = None
        self._cycles = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._load_state()

        metrics = server.metrics
        self._folds = metrics.counter(
            "pio_continuous_folds_total",
            "Continuous-training cycle outcomes",
            labelnames=("kind",),
        )
        metrics.gauge_callback(
            "pio_continuous_feed_lag_ops",
            self.watcher.feed_lag,
            "Changefeed ops between the continuous cursor and the feed head",
        )
        metrics.gauge_callback(
            "pio_continuous_candidate_age_seconds",
            self._candidate_age_s,
            "Age of the in-flight continuous candidate (0 = none)",
        )

    # -- durable state ----------------------------------------------------
    def _load_state(self) -> None:
        try:
            with open(self._state_path) as fh:
                state = json.load(fh)
        except (OSError, ValueError):
            return
        with self._lock:
            self._candidate = state.get("candidate")
            self._quarantined = list(state.get("quarantined", []))
            self._last_freshness_s = state.get("lastFreshnessS")
            self._last_cycle = state.get("lastCycle")

    def _persist_state(self) -> None:
        """Crash-safe controller state (call with ``_lock`` held)."""
        atomic_write_bytes(
            self._state_path,
            json.dumps(
                {
                    "candidate": self._candidate,
                    "quarantined": self._quarantined,
                    "lastFreshnessS": self._last_freshness_s,
                    "lastCycle": self._last_cycle,
                }
            ).encode(),
        )

    def _observe_feedback(self, event) -> None:
        """Watcher tap (outside the watcher lock): join one feedback
        event to the served-list LRU. Never raises — the watcher
        swallows, but a monitor-less server must not even log."""
        quality = getattr(self.server, "quality", None)
        if quality is not None:
            quality.record_feedback(event.user, event.item)

    # -- gauge callbacks (scrape threads: lock every shared read) ---------
    def _candidate_age_s(self) -> float:
        with self._lock:
            cand = self._candidate
            if cand is None or "createdS" not in cand:
                return 0.0
            return max(0.0, self.clock() - float(cand["createdS"]))

    def _fold_event(self, kind: str) -> None:
        """One cycle outcome: counter + flight-recorder timeline entry
        (promote/kill/escalate events are exactly what a post-mortem of
        the loop needs in order, docs/slo.md)."""
        self._folds.inc(1, kind=kind)
        flight_record("continuous", "continuous.fold", outcome=kind)

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        """Run the background tick loop (idempotent)."""
        # the Event is its own synchronizer — touched outside the
        # controller lock so the loop thread's bare .wait() stays
        # consistent with every other access
        self._stop.clear()
        with self._lock:
            self._paused = False
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._loop, name="continuous", daemon=True
            )
            self._thread.start()
        self._watch()

    def _loop(self) -> None:
        while not self._stop.wait(self.config.poll_interval_s):
            try:
                self.tick()
            except Exception:  # the loop must survive anything
                logger.exception("continuous tick failed")

    def _watch(self) -> None:
        """Register the loop's stall expectations. Generous gap: a tick
        that escalates to a full retrain legitimately blocks the loop
        for the whole training run (docs/slo.md)."""
        if self._watchdog is None:
            return
        gap = max(8 * self.config.poll_interval_s, 900.0)
        self._watchdog.expect("continuous.tick", max_gap_s=gap)
        self._watchdog.expect("continuous.feed", max_gap_s=gap)

    def _unwatch(self) -> None:
        if self._watchdog is not None:
            self._watchdog.unexpect("continuous.tick")
            self._watchdog.unexpect("continuous.feed")

    def stop(self) -> None:
        self._unwatch()
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)

    def pause(self) -> dict:
        # a deliberately paused loop is not a stall: stop watching the
        # beats until it resumes (docs/slo.md)
        self._unwatch()
        with self._lock:
            self._paused = True
        return self.status()

    def resume_watching(self) -> dict:
        with self._lock:
            self._paused = False
        if self._thread is not None:
            self._watch()
        return self.status()

    def trigger(self, full: bool = False) -> dict:
        """Force a cycle on the next tick regardless of thresholds
        (``pio continuous trigger``)."""
        with self._lock:
            self._trigger = True
            self._force_full = self._force_full or full
            self._cooldown_until = 0.0
            self._next_submit_s = 0.0
        return self.status()

    # -- the tick ---------------------------------------------------------
    def tick(self) -> dict:
        """One deterministic controller step (the background loop and the
        tests both drive this). Never raises on feed/train/storage
        trouble — failures land in ``status()["lastError"]``."""
        if self._watchdog is not None:
            self._watchdog.beat("continuous.tick")
        with self._lock:
            if self._ticking:
                return self.status()
            self._ticking = True
        try:
            self._tick_inner()
        finally:
            with self._lock:
                self._ticking = False
        return self.status()

    def _tick_inner(self) -> None:
        with self._lock:
            if self._paused:
                return
        now = self.clock()
        try:
            self.watcher.poll()
            with self._lock:
                self._last_error = None
        except FeedGap as exc:
            # the delta stream is incomplete: only a full retrain (which
            # reads the whole event store) can cover what the feed lost
            flight_record("continuous", "continuous.gap", error=str(exc))
            with self._lock:
                self._force_full = True
                self._feed_gap = True
                self._trigger = True
                self._last_error = f"feed gap: {exc}"
            logger.warning("continuous: %s — escalating to full retrain", exc)
        except Exception as exc:
            with self._lock:
                self._last_error = f"feed poll failed: {exc}"
            return

        if self._check_rollout(now):
            return  # a candidate is still in flight: one cycle at a time

        with self._lock:
            if now < self._cooldown_until:
                return
            pending = self.watcher.pending_count()
            oldest_ms = self.watcher.oldest_pending_ms()
            stale_s = (
                max(0.0, self.wall() * 1000.0 - oldest_ms) / 1000.0
                if oldest_ms
                else 0.0
            )
            due = (
                self._trigger
                or pending >= self.config.min_events
                or (pending > 0 and stale_s >= self.config.max_staleness_s)
            )
            if not due:
                return
            self._trigger = False
            force_full = self._force_full
        self._run_cycle(force_full)

    # -- rollout monitoring ----------------------------------------------
    def _check_rollout(self, now: float) -> bool:
        """Advance the in-flight candidate's lifecycle. Returns True while
        a candidate still occupies the loop."""
        with self._lock:
            cand = self._candidate
        if cand is None:
            return False
        if not cand.get("submitted"):
            # whether the submit landed or backed off, this candidate
            # still claims the loop — the pending delta it was built
            # from stays uncommitted until the rollout finishes, and a
            # same-tick second cycle would re-train that same delta
            self._try_submit(cand, now)
            return True
        rollout = self.server.rollout
        plan = rollout.plan if rollout is not None else None
        if plan is None or plan.candidate_instance_id != cand["instanceId"]:
            # replaced/aborted out-of-band (operator started their own
            # rollout, or the plan vanished): drop the claim, keep the
            # delta — it folds into the next candidate
            with self._lock:
                self._candidate = None
                self._persist_state()
            logger.warning(
                "continuous: candidate %s lost its rollout (plan %s); "
                "the pending delta stays queued",
                cand["instanceId"], plan.id if plan else None,
            )
            return False
        if plan.stage == ROLLOUT_LIVE:
            freshness_s = None
            if cand.get("oldestMs"):
                freshness_s = max(
                    0.0, self.wall() * 1000.0 - cand["oldestMs"]
                ) / 1000.0
            if cand.get("resync"):
                # a gap retrain covered the feed's lost history from the
                # store itself: jump the cursor to the head (a plain
                # commit would leave position/generation stale and the
                # gap would re-fire on every later poll)
                try:
                    self.watcher.resync()
                    with self._lock:
                        self._feed_gap = False
                        self._force_full = False
                except Exception as exc:
                    # the feed is still unreachable: keep the gap flag;
                    # the next successful cycle retries the resync
                    with self._lock:
                        self._last_error = f"resync failed: {exc}"
            else:
                upto = cand["uptoSeq"]
                try:
                    # flat watcher: one int; partitioned: the per-
                    # partition map take_batch() produced (string keys
                    # after the JSON round-trip through the durable
                    # candidate state)
                    self.watcher.commit(
                        upto if isinstance(upto, dict) else int(upto)
                    )
                except (TypeError, ValueError) as exc:
                    # a resharding restart crossed watcher shapes (a
                    # per-partition cursor map against a flat watcher,
                    # or vice versa): the stored seqs are meaningless
                    # against the new feed layout. Never wedge the LIVE
                    # path — resync to the new head and force a full
                    # retrain to cover whatever sits in between.
                    logger.warning(
                        "continuous: candidate cursor %r does not match "
                        "the current feed layout (%s); resyncing and "
                        "forcing a full retrain", upto, exc,
                    )
                    with self._lock:
                        self._force_full = True
                        self._trigger = True
                    try:
                        self.watcher.resync()
                    except Exception as resync_exc:
                        with self._lock:
                            self._last_error = (
                                f"resync failed: {resync_exc}"
                            )
            with self._lock:
                self._candidate = None
                self._last_freshness_s = freshness_s
                self._submit_attempts = 0
                if self._last_cycle is not None:
                    self._last_cycle["outcome"] = "live"
                    self._last_cycle["freshnessS"] = freshness_s
                self._persist_state()
            self._fold_event("promoted")
            logger.info(
                "continuous: candidate %s is LIVE (freshness %.3fs)",
                cand["instanceId"], freshness_s or -1.0,
            )
            return False
        if plan.stage in (ROLLOUT_ROLLED_BACK, ROLLOUT_ABORTED):
            with self._lock:
                if cand["instanceId"] not in self._quarantined:
                    self._quarantined.append(cand["instanceId"])
                self._candidate = None
                # a fold the gates rejected means the incremental step
                # cannot be trusted on this delta: next cycle retrains
                self._force_full = True
                self._cooldown_until = (
                    now + self.config.quarantine_backoff_s
                )
                if self._last_cycle is not None:
                    self._last_cycle["outcome"] = plan.stage.lower()
                self._persist_state()
            self._fold_event("quarantined")
            logger.warning(
                "continuous: candidate %s was %s by the rollout gates; "
                "quarantined, cooling down %.0fs, next cycle is a full "
                "retrain",
                cand["instanceId"], plan.stage,
                self.config.quarantine_backoff_s,
            )
            return False
        return True  # SHADOW/CANARY: keep monitoring

    def _try_submit(self, cand: dict, now: float) -> bool:
        """Submit a produced-but-unsubmitted candidate. Returns True on
        success; on a busy rollout, schedules a jittered retry."""
        with self._lock:
            if now < self._next_submit_s:
                return False
        from ..rollout.manager import RolloutError

        try:
            self.server.rollout.start(
                candidate_instance_id=cand["instanceId"],
                percent=self.config.rollout_percent,
                gates=(
                    dict(self.config.rollout_gates)
                    if self.config.rollout_gates
                    else None
                ),
                reason="continuous controller auto-submit",
            )
        except RolloutError as exc:
            # another rollout is in flight (operator- or us-before-crash):
            # back off on the shared full-jitter schedule
            with self._lock:
                delay = self._retry.delay_for(min(self._submit_attempts, 6))
                self._submit_attempts += 1
                self._next_submit_s = now + max(
                    delay, self.config.poll_interval_s
                )
                self._last_error = f"rollout busy: {exc}"
            return False
        except Exception as exc:
            with self._lock:
                self._last_error = f"rollout start failed: {exc}"
                self._quarantined.append(cand["instanceId"])
                self._candidate = None
                self._cooldown_until = now + self.config.quarantine_backoff_s
                self._persist_state()
            self._fold_event("quarantined")
            logger.exception(
                "continuous: submitting candidate %s failed", cand["instanceId"]
            )
            return False
        with self._lock:
            cand["submitted"] = True
            self._candidate = cand
            self._submit_attempts = 0
            self._persist_state()
        logger.info(
            "continuous: candidate %s submitted to the rollout plane",
            cand["instanceId"],
        )
        return True

    # -- one training cycle ----------------------------------------------
    def _run_cycle(self, force_full: bool) -> None:
        now = self.clock()
        batch = self.watcher.take_batch()
        if batch is None and not force_full:
            return
        dep = self.server.deployment
        pd = None
        if not force_full and batch is not None:
            # the prepared data is needed for the fold anyway; reading it
            # before the decision makes the delta fraction exact instead
            # of an entity-count proxy (full retrain re-reads internally
            # — that's the existing path, unchanged)
            pd = self._read_prepared(dep)
        mode, reason = self._decide(dep, batch, force_full, pd)
        cycle: dict = {
            "mode": mode,
            "reason": reason,
            "deltaEvents": len(batch.events) if batch else 0,
            "atS": round(now, 3),
        }
        # what the candidate will commit at LIVE: the merged cursor by
        # default; the partitioned fold path narrows it to the partitions
        # whose fold actually completed
        commit_upto = batch.upto_seq if batch else self.watcher.position
        commit_oldest = batch.oldest_event_ms if batch else None
        try:
            if mode == FOLD_IN:
                part_batches = None
                take_batches = getattr(self.watcher, "take_batches", None)
                if take_batches is not None and dep.algorithms and all(
                    hasattr(a, "fold_in_partitioned") for a in dep.algorithms
                ):
                    part_batches = take_batches()
                if part_batches and len(part_batches) > 1:
                    (
                        instance_id, fold_stats, completed, skipped,
                    ) = self._fold_in_candidate_partitioned(
                        dep, part_batches, pd
                    )
                    if instance_id is None:
                        # drift escalation: NOTHING was committed —
                        # reporting partitions as "completed" here would
                        # mislead the status surface
                        cycle["foldPartitions"] = {
                            "escalated": sorted(part_batches),
                        }
                    else:
                        cycle["foldPartitions"] = {
                            "completed": completed, "skipped": skipped,
                        }
                    if instance_id is not None:
                        # only the completed partitions' cursors advance
                        # at LIVE; a skipped partition keeps its delta
                        # pending (re-folded next cycle, never lost)
                        commit_upto = {
                            str(i): part_batches[i].upto_seq
                            for i in completed
                        }
                        commit_oldest = min(
                            part_batches[i].oldest_event_ms
                            for i in completed
                        )
                        cycle["deltaEvents"] = sum(
                            len(part_batches[i].events) for i in completed
                        )
                else:
                    instance_id, fold_stats = self._fold_in_candidate(
                        dep, batch, pd
                    )
                if fold_stats is not None:
                    cycle["foldIn"] = fold_stats
                if instance_id is None:  # drift escalation inside the fold
                    mode = FULL_RETRAIN
                    cycle["mode"] = mode
                    cycle["reason"] = (
                        f"fold-in RMSE drift "
                        f"{fold_stats['rmseDrift'] if fold_stats else '?'} "
                        f"exceeded policy "
                        f"{self.config.policy.max_rmse_drift}: escalated"
                    )
                    self._fold_event("escalated")
            if mode == FULL_RETRAIN:
                instance_id = self._full_retrain_candidate(dep)
        except Exception as exc:
            with self._lock:
                self._last_error = f"{mode} failed: {exc}"
                cycle["outcome"] = "error"
                cycle["error"] = str(exc)
                self._last_cycle = cycle
                self._persist_state()
            logger.exception("continuous: %s cycle failed", mode)
            return
        self._fold_event(mode)
        with self._lock:
            self._cycles += 1
            self._force_full = False

        # offline scoring against the live baseline's served predictions
        score = self._offline_score(instance_id)
        cycle["offlineScore"] = score
        if not score.get("ok", True):
            with self._lock:
                self._quarantined.append(instance_id)
                # like a gate rollback: the candidate this delta produced
                # cannot be trusted, so the next cycle must NOT re-fold
                # the same delta into a byte-identical candidate (an
                # infinite quarantine loop) — it retrains fully instead
                self._force_full = True
                self._cooldown_until = (
                    self.clock() + self.config.quarantine_backoff_s
                )
                cycle["outcome"] = "offline_quarantined"
                self._last_cycle = cycle
                self._persist_state()
            self._fold_event("quarantined")
            logger.warning(
                "continuous: candidate %s failed offline scoring (%s); "
                "quarantined before submission",
                instance_id, score.get("reason"),
            )
            return

        with self._lock:
            needs_resync = self._feed_gap
        cand = {
            "instanceId": instance_id,
            "uptoSeq": commit_upto,
            "oldestMs": commit_oldest,
            "mode": mode,
            "submitted": False,
            "createdS": now,
            "resync": needs_resync,
        }
        with self._lock:
            self._candidate = cand
            cycle["candidateInstanceId"] = instance_id
            cycle["outcome"] = "submitted"
            self._last_cycle = cycle
            self._persist_state()
        self._try_submit(cand, self.clock())

    def _read_prepared(self, dep):
        """Read + prepare the current training data through the engine's
        own components (the fold path's data access). None when the
        engine cannot fold anyway or the read fails (→ full retrain)."""
        if not dep.algorithms or not all(
            hasattr(a, "fold_in")
            and getattr(a, "fold_in_supported", True)
            for a in dep.algorithms
        ):
            return None
        try:
            engine = self.server.engine
            ctx = self.server.ctx
            ep = dep.engine_params
            data_source = engine._data_source(ep)
            preparator = engine._preparator(ep)
            return preparator.prepare(ctx, data_source.read_training(ctx))
        except Exception as exc:
            logger.warning(
                "continuous: reading data for fold-in failed (%s); "
                "deciding without it", exc,
            )
            return None

    def _decide(self, dep, batch, force_full: bool, pd) -> Tuple[str, str]:
        if force_full:
            return FULL_RETRAIN, "escalation forced (feed gap or quarantine)"
        if batch is None:
            return FULL_RETRAIN, "no delta batch (explicit trigger)"
        fold_available = pd is not None
        known = new = total = 0
        if fold_available:
            try:
                model = dep.models[0]
                known = len(model.user_map) + len(model.item_map)
                new = sum(
                    1 for u in batch.user_ids if model.user_map.get(u) is None
                ) + sum(
                    1 for i in batch.item_ids if model.item_map.get(i) is None
                )
                # exact corpus size when the prepared data exposes its
                # interaction array; entity count as the lower-bound proxy
                # otherwise
                ratings = getattr(pd, "ratings", None)
                total = len(ratings) if ratings is not None else known
            except (AttributeError, TypeError):
                fold_available = False
        return decide_mode(
            self.config.policy,
            total_events=max(total, len(batch.events)),
            delta_events=len(batch.events),
            known_entities=known,
            new_entities=new,
            fold_in_available=fold_available,
        )

    def _fold_in_candidate(
        self, dep, batch, pd
    ) -> Tuple[Optional[str], Optional[dict]]:
        """Produce a fold-in candidate from the prepared data through
        the existing persist path. Returns ``(instance_id, stats)``;
        ``(None, stats)`` when RMSE drift demands escalation."""
        ctx = self.server.ctx
        with self.server.tracer.span("continuous.fold"):
            models = []
            stats_json: Optional[dict] = None
            for algo, model in zip(dep.algorithms, dep.models):
                folded, stats = algo.fold_in(
                    ctx, model, pd, batch.user_ids, batch.item_ids,
                    policy=self.config.policy,
                )
                if stats.rmse_drift > self.config.policy.max_rmse_drift:
                    return None, stats.to_json()
                stats_json = stats.to_json()
                models.append(folded)
            instance_id = self._persist_candidate(dep, models, FOLD_IN)
        return instance_id, stats_json

    def _fold_in_candidate_partitioned(
        self, dep, part_batches, pd
    ) -> Tuple[Optional[str], Optional[dict], List[int], List[int]]:
        """Concurrent per-partition folds on a bounded pool
        (docs/continuous.md#partitioned-folds): every algorithm folds
        each partition's delta against the same base model; a partition
        whose fold missed ``fold_partition_timeout_s`` (or raised) is
        SKIPPED — counted, excluded from the commit set, its delta
        re-folds next cycle — so a slow partition never blocks another
        partition's commit. Returns ``(instance_id | None, stats_json,
        completed, skipped)``; ``None`` = drift escalation, exactly like
        the merged fold path."""
        ctx = self.server.ctx
        cfg = self.config
        parts = {
            i: (b.user_ids, b.item_ids) for i, b in part_batches.items()
        }
        with self.server.tracer.span("continuous.fold"):
            models = []
            stats_json: Optional[dict] = None
            completed_all: Optional[set] = None
            for algo, model in zip(dep.algorithms, dep.models):
                folded, stats, completed = algo.fold_in_partitioned(
                    ctx, model, pd, parts,
                    policy=cfg.policy,
                    max_workers=cfg.fold_workers,
                    timeout_s=cfg.fold_partition_timeout_s,
                )
                if stats.rmse_drift > cfg.policy.max_rmse_drift:
                    return None, stats.to_json(), sorted(parts), []
                stats_json = stats.to_json()
                models.append(folded)
                # multi-algorithm engines commit the INTERSECTION: a
                # partition folded into one model but skipped by another
                # re-folds next cycle (convergent — the watcher's replay
                # contract)
                completed_all = (
                    set(completed)
                    if completed_all is None
                    else completed_all & set(completed)
                )
            done = sorted(completed_all or [])
            skipped = sorted(set(parts) - set(done))
            for _ in skipped:
                self._fold_event("partition_skipped")
            if not done:
                raise RuntimeError(
                    f"no partition fold completed (partitions "
                    f"{sorted(parts)} all timed out or failed)"
                )
            instance_id = self._persist_candidate(dep, models, FOLD_IN)
        return instance_id, stats_json, done, skipped

    def _full_retrain_candidate(self, dep) -> str:
        """The existing train/persist path, parameter-identical to the
        deployed baseline."""
        from ..controller.engine import WorkflowParams
        from ..workflow.core_workflow import run_train

        inst = dep.instance
        with self.server.tracer.span("continuous.retrain"):
            return run_train(
                self.server.engine,
                dep.engine_params,
                self.server.registry,
                engine_id=inst.engine_id,
                engine_version=inst.engine_version,
                engine_variant=inst.engine_variant,
                engine_factory=inst.engine_factory,
                # the stable batch slug makes the derived checkpoint dir
                # stable across retrains: a killed retrain's committed
                # checkpoints are found by the next one, which resumes
                # from the latest valid step (docs/checkpoint.md)
                workflow_params=WorkflowParams(
                    batch="continuous-retrain",
                    checkpoint_every=self.config.retrain_checkpoint_every,
                ),
                # run_train stops its ctx when done — give it its own
                # instead of the server's serving context
            )

    def _persist_candidate(self, dep, models, mode: str) -> str:
        """Fold-in persist: the same instance-record + model-blob path a
        full ``run_train`` walks (``workflow/core_workflow.py``), so a
        fold-in candidate is indistinguishable downstream — deployable,
        rollout-eligible, listed by the dashboard."""
        import pickle

        from ..storage import (
            STATUS_COMPLETED,
            Model,
            new_engine_instance,
            utcnow,
        )
        from ..workflow.context import pio_env_vars

        inst = dep.instance
        md = self.server.registry.get_metadata()
        env = pio_env_vars()
        env["PIO_CONTINUOUS"] = mode
        record = new_engine_instance(
            engine_id=inst.engine_id,
            engine_version=inst.engine_version,
            engine_variant=inst.engine_variant,
            engine_factory=inst.engine_factory,
            batch=f"continuous-{mode}",
            env=env,
            **{
                k: getattr(inst, k)
                for k in (
                    "data_source_params",
                    "preparator_params",
                    "algorithms_params",
                    "serving_params",
                )
            },
        )
        instance_id = md.engine_instance_insert(record)
        persisted = self.server.engine.make_serializable_models(
            self.server.ctx, dep.engine_params, instance_id, models
        )
        self.server.registry.get_models().insert(
            Model(id=instance_id, models=pickle.dumps(persisted))
        )
        stored = md.engine_instance_get(instance_id)
        md.engine_instance_update(
            dataclasses.replace(
                stored, status=STATUS_COMPLETED, end_time=utcnow()
            )
        )
        return instance_id

    # -- offline scoring ---------------------------------------------------
    def _offline_score(self, instance_id: str) -> dict:
        """Replay recent variant-tagged ``pio_pr`` feedback queries
        through the candidate and compare against the predictions the
        live baseline actually served (``docs/continuous.md#offline-
        scoring``). No feedback yet → the gate abstains (the rollout's
        own shadow stage still guards)."""
        from ..obs.quality import scores_from_result
        from ..obs.sketch import QuantileSketch
        from ..rollout.plan import BASELINE, prediction_divergence
        from ..storage.events import EventFilter
        from ..workflow.serving import (
            ServerConfig,
            decode_query,
            encode_result,
            prepare_deployment,
        )

        out: dict = {"samples": 0, "ok": True}
        # offline score-drift check (docs/observability.md#quality): the
        # candidate's replayed score distribution vs the quality
        # monitor's pinned baseline, gated by the same max_score_psi the
        # rollout gates carry — a drifted candidate is quarantined before
        # submission, not after burning a shadow stage
        quality = getattr(self.server, "quality", None)
        max_psi = 0.0
        try:
            max_psi = float(
                (self.config.rollout_gates or {}).get("max_score_psi", 0.0)
                or 0.0
            )
        except (TypeError, ValueError):
            max_psi = 0.0
        score_sketch = (
            QuantileSketch(rel_err=quality.config.rel_err)
            if quality is not None
            else None
        )
        with self.server.tracer.span("continuous.score"):
            try:
                events = list(
                    self.server.registry.get_events().find(
                        self.config.app_id,
                        EventFilter(
                            entity_type="pio_pr",
                            event_names=["predict"],
                            limit=self.config.score_window,
                            reversed=True,
                        ),
                    )
                )
            except Exception as exc:
                out["reason"] = f"feedback read failed: {exc}"
                return out  # abstain: scoring must not block the loop
            if not events:
                out["reason"] = "no feedback events to score against"
                return out
            try:
                cfg = dataclasses.replace(
                    self.server.config, engine_instance_id=instance_id
                )
                cand_dep = prepare_deployment(
                    self.server.engine, self.server.registry, cfg,
                    self.server.ctx,
                )
            except Exception as exc:
                out["ok"] = False
                out["reason"] = f"candidate unloadable: {exc}"
                return out
            divergences: List[float] = []
            for event in events:
                props = event.properties.to_dict()
                if props.get("variant", BASELINE) != BASELINE:
                    continue  # score against what the BASELINE served
                payload = props.get("query")
                served = props.get("prediction")
                if payload is None or served is None:
                    continue
                try:
                    query = decode_query(cand_dep.algorithms, payload)
                    predictions = [
                        algo.predict(model, query)
                        for algo, model in zip(
                            cand_dep.algorithms, cand_dep.models
                        )
                    ]
                    replayed = cand_dep.serving.serve(query, predictions)
                    replayed_enc = encode_result(replayed)
                    divergences.append(
                        prediction_divergence(served, replayed_enc)
                    )
                    if score_sketch is not None:
                        score_sketch.extend(
                            scores_from_result(replayed_enc)[1]
                        )
                except Exception:
                    divergences.append(1.0)  # an unservable query is a
                    # maximal divergence, not a scoring crash
            out["samples"] = len(divergences)
            if divergences:
                mean_div = sum(divergences) / len(divergences)
                out["meanDivergence"] = round(mean_div, 6)
                if (
                    len(divergences) >= self.config.min_score_samples
                    and mean_div > self.config.max_offline_divergence
                ):
                    out["ok"] = False
                    out["reason"] = (
                        f"mean offline divergence {mean_div:.4f} exceeds "
                        f"{self.config.max_offline_divergence:.4f} over "
                        f"{len(divergences)} replayed queries"
                    )
            if (
                out["ok"]
                and score_sketch is not None
                and score_sketch.count
            ):
                psi_value = quality.psi_for_sketch(score_sketch)
                if psi_value is not None:
                    out["scorePsi"] = round(psi_value, 6)
                    if max_psi > 0 and psi_value > max_psi:
                        out["ok"] = False
                        out["reason"] = (
                            f"offline score PSI {psi_value:.4f} exceeds "
                            f"{max_psi:.4f} vs the baseline score "
                            "distribution"
                        )
            return out

    # -- status -----------------------------------------------------------
    def state(self) -> str:
        with self._lock:
            if self._paused:
                return PAUSED
            if self._candidate is not None:
                return (
                    MONITORING
                    if self._candidate.get("submitted")
                    else SUBMIT_PENDING
                )
            if self.clock() < self._cooldown_until:
                return COOLDOWN
            return WATCHING

    def status(self) -> dict:
        """The ``GET /continuous.json`` / ``pio continuous status`` body."""
        state = self.state()
        # watcher/quality reads take their own locks; keep them outside
        # the controller lock (one lock at a time, no ordering to get
        # wrong)
        feed_lag = self.watcher.feed_lag()
        pending = self.watcher.pending_count()
        quality = getattr(self.server, "quality", None)
        online_quality = (
            quality.online_quality() if quality is not None else None
        )
        with self._lock:
            out: dict = {
                "enabled": True,
                "state": state,
                "appId": self.config.app_id,
                "cursorSeq": self.watcher.cursor_seq,
                "position": self.watcher.position,
                "feedLagOps": feed_lag,
                "pendingEvents": pending,
                "cycles": self._cycles,
                "quarantined": list(self._quarantined),
            }
            if online_quality is not None:
                # the feedback join's hit-rate / served-rank digest —
                # the loop's online-quality number next to divergence
                out["onlineQuality"] = online_quality
            if self._candidate is not None:
                out["candidate"] = dict(self._candidate)
            if self._last_cycle is not None:
                out["lastCycle"] = dict(self._last_cycle)
            if self._last_freshness_s is not None:
                out["lastFreshnessS"] = round(self._last_freshness_s, 3)
            if self._last_error:
                out["lastError"] = self._last_error
            if self.clock() < self._cooldown_until:
                out["cooldownRemainingS"] = round(
                    self._cooldown_until - self.clock(), 3
                )
        return out
