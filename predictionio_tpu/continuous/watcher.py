"""Feed watcher: changefeed → rating-delta accumulation with a durable
cursor.

The ingestion edge of the continuous-learning plane
(``docs/continuous.md``): tails the PR-3 changefeed — the in-process
:class:`~predictionio_tpu.storage.oplog.OpLog` op stream via
:class:`LocalFeed`, or a storage server's ``GET /replicate/changes``
route via :class:`RemoteFeed` — filters the feedback/rating ops of one
app through the engine's value rules (the same rate/buy rules the
training infeed uses, ``workflow/infeed.py``), and accumulates a
:class:`DeltaBatch` with freshness accounting.

Cursor discipline (the restart-resumes-exact contract, mirroring the
replica's ``applied.json``): the watcher reads forward from an
in-memory *position* but only advances the **durable cursor**
(``continuous_cursor.json``, written crash-safely) when the controller
*commits* a consumed batch — i.e. after the delta actually became a live
model. A restart anywhere in between re-reads the uncommitted suffix;
re-folding the same events is convergent, so replay is harmless, and no
acked feedback is ever skipped.

A :class:`FeedGap` (sequence gap — the feed no longer holds our cursor —
or a generation change — the primary store was replaced) means the delta
stream is no longer complete: incremental folding must stop and the
controller escalates to a full retrain (which reads the whole event
store, covering whatever the feed dropped) before :meth:`FeedWatcher.
resync` jumps the cursor to the feed head.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
from typing import Callable, Dict, List, Optional

from ..storage.event import parse_event_time, to_millis
from ..storage.oplog import OpLog, OpLogGap
from ..utils.durability import atomic_write_bytes

logger = logging.getLogger(__name__)

__all__ = [
    "CURSOR_NAME",
    "ChangefeedSubscriber",
    "DeltaBatch",
    "DeltaEvent",
    "FeedGap",
    "FeedWatcher",
    "LocalFeed",
    "PartitionedFeedWatcher",
    "RemoteFeed",
    "handoff_cursors",
    "make_watcher",
]

CURSOR_NAME = "continuous_cursor.json"


class FeedGap(Exception):
    """Incremental tailing cannot continue (seq gap or generation
    change): the pending delta is incomplete — full retrain, then
    :meth:`FeedWatcher.resync`."""


class LocalFeed:
    """Changefeed source over an in-process :class:`OpLog` (the query
    server sharing a host — and an oplog directory — with its storage
    primary, or a test driving everything in one process)."""

    def __init__(self, oplog: OpLog):
        self._oplog = oplog

    def fetch(self, since: int, limit: int) -> dict:
        try:
            entries, last_seq = self._oplog.read_since(since, limit)
        except OpLogGap as exc:
            raise FeedGap(str(exc)) from exc
        return {
            "changes": [{"seq": s, "op": o} for s, o in entries],
            "lastSeq": last_seq,
            "generation": self._oplog.generation,
            "oldestSeq": self._oplog.oldest_seq,
        }

    def checkpoint(self) -> dict:
        return self._oplog.checkpoint()


class RemoteFeed:
    """Changefeed source over a storage server's replication routes
    (``GET /replicate/changes`` / ``/replicate/checkpoint``) — the same
    wire a warm-standby replica tails (``storage/replica.py``)."""

    def __init__(self, primary_url: str, timeout: float = 10.0):
        self._primary = primary_url.rstrip("/")
        self._timeout = timeout

    def fetch(self, since: int, limit: int) -> dict:
        from ..storage.remote import RemoteStorageError, _json, _request

        url = (
            f"{self._primary}/replicate/changes"
            f"?since={since}&limit={limit}"
        )
        try:
            with _request(url, timeout=self._timeout) as resp:
                return _json(resp)
        except RemoteStorageError as exc:
            if exc.code == 410:  # the log no longer holds our cursor
                raise FeedGap(str(exc)) from exc
            raise

    def checkpoint(self) -> dict:
        from ..storage.remote import _json, _request

        url = f"{self._primary}/replicate/checkpoint"
        with _request(url, timeout=self._timeout) as resp:
            return _json(resp)


class ChangefeedSubscriber:
    """Pushed invalidation: a daemon thread tails a changefeed source
    (:class:`LocalFeed` / :class:`RemoteFeed`) from the current head and
    hands every new batch of ops to ``on_ops(ops, gap=...)`` — the
    router's near-zero-staleness epoch flush
    (docs/fleet.md#shared-cache-tier).

    Robustness contract (the subscriber is a *signal*, never the source
    of truth):

    - a :class:`FeedGap` resyncs to the head and reports the hole as one
      ``on_ops([], gap=True)`` wakeup — the owner must treat "I missed
      an unknown window" as "assume the epoch moved";
    - a fetch error never kills the thread: it is recorded
      (``last_error``, a warning log) and retried after a backoff;
    - :meth:`alive` answers False the moment the last *successful*
      fetch is older than ``stale_after_s`` (or the thread died), so an
      owner polling :meth:`alive` falls back to its own cadence instead
      of trusting a wedged push plane — a dead subscriber can never
      silently freeze the owner's view (the PR-14 headroom fix).

    ``clock`` is injectable but the thread sleeps on a real
    ``threading.Event`` — tests that need determinism drive
    :meth:`poll_once` directly without :meth:`start`.
    """

    def __init__(
        self,
        feed,
        on_ops: Callable[[List[dict], bool], None],
        poll_s: float = 0.05,
        batch_limit: int = 500,
        stale_after_s: Optional[float] = None,
        clock: Callable[[], float] = None,
        name: str = "changefeed-subscriber",
    ):
        import time as _time

        self._feed = feed
        self._on_ops = on_ops
        self.poll_s = max(0.005, float(poll_s))
        self.batch_limit = int(batch_limit)
        self.stale_after_s = (
            float(stale_after_s)
            if stale_after_s is not None
            else max(1.0, 20.0 * self.poll_s)
        )
        self._clock = clock or _time.monotonic
        self._name = name
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._since: Optional[int] = None  # None = not yet at the head
        self._generation: Optional[int] = None
        self._last_ok: Optional[float] = None
        self._last_error: Optional[str] = None
        self.events_seen = 0
        self.gaps = 0

    # -- health (any thread) ----------------------------------------------
    def alive(self) -> bool:
        """True only while the push plane is *demonstrably* working: the
        thread runs AND the last successful fetch is fresh. Everything
        else — never started, crashed, wedged on an unreachable feed —
        is False, and the owner's poll watchdog takes over."""
        thread = self._thread
        if thread is None or not thread.is_alive():
            return False
        with self._lock:
            last_ok = self._last_ok
        return (
            last_ok is not None
            and self._clock() - last_ok <= self.stale_after_s
        )

    def status(self) -> dict:
        """The ``/router.json`` subscriber block."""
        with self._lock:
            last_ok = self._last_ok
            out = {
                "alive": False,  # filled below, outside the lock
                "eventsSeen": self.events_seen,
                "gaps": self.gaps,
                "lastError": self._last_error,
                "cursor": self._since,
                "staleAfterS": self.stale_after_s,
                "lastOkAgoS": (
                    round(self._clock() - last_ok, 3)
                    if last_ok is not None
                    else None
                ),
            }
        out["alive"] = self.alive()
        return out

    # -- tailing -----------------------------------------------------------
    def _resync(self) -> None:
        """Jump the cursor to the feed head (initial attach, or after a
        gap): pushed invalidation only cares about *new* ops."""
        cp = self._feed.checkpoint()
        with self._lock:
            self._since = int(cp.get("seq", cp.get("lastSeq", 0)))
            self._generation = cp.get("generation")
            self._last_ok = self._clock()

    def poll_once(self) -> int:
        """One fetch → callback round; returns how many ops were
        delivered. Raises nothing: errors are recorded and swallowed
        here (the loop must outlive any feed outage), gaps surface to
        the owner as ``on_ops([], gap=True)``."""
        gap = False
        ops: List[dict] = []
        try:
            if self._since is None:
                self._resync()
                return 0
            page = self._feed.fetch(self._since, self.batch_limit)
            generation = page.get("generation")
            with self._lock:
                if (
                    self._generation is not None
                    and generation is not None
                    and generation != self._generation
                ):
                    gap = True  # primary replaced: unknown history
                self._generation = generation
            if gap:
                self._resync()
            else:
                ops = [c.get("op") for c in page.get("changes", ())]
                with self._lock:
                    self._since = int(page.get("lastSeq", self._since))
                    self._last_ok = self._clock()
                    self._last_error = None
                    self.events_seen += len(ops)
        except FeedGap as exc:
            gap = True
            with self._lock:
                self._last_error = f"gap: {exc}"
            try:
                self._resync()
            except Exception as resync_exc:
                with self._lock:
                    self._last_error = (
                        f"resync failed: {resync_exc!r}"
                    )
                logger.warning(
                    "%s: resync after gap failed: %s",
                    self._name, resync_exc,
                )
                return 0
        except Exception as exc:
            # the push plane degraded — recorded here, surfaced via
            # alive()/status(); the owner's poll watchdog covers the
            # outage (docs/fleet.md#shared-cache-tier failure modes)
            with self._lock:
                self._last_error = f"{type(exc).__name__}: {exc}"
            logger.warning(
                "%s: fetch failed (owner falls back to polling): %s",
                self._name, exc,
            )
            return 0
        if gap:
            with self._lock:
                self.gaps += 1
        if ops or gap:
            try:
                self._on_ops(ops, gap)
            except Exception:
                logger.exception(
                    "%s: on_ops callback failed", self._name
                )
        return len(ops)

    def _run(self) -> None:
        while not self._stop.is_set():
            delivered = self.poll_once()
            if delivered == 0:
                # idle or erroring: wait out the interval (errors wait a
                # longer beat so a dead feed isn't hammered)
                beat = self.poll_s
                with self._lock:
                    if self._last_error is not None:
                        beat = min(
                            self.stale_after_s, self.poll_s * 10.0
                        )
                self._stop.wait(beat)

    def start(self) -> "ChangefeedSubscriber":
        self._thread = threading.Thread(
            target=self._run, name=self._name, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=2.0)


@dataclasses.dataclass(frozen=True)
class DeltaEvent:
    """One extracted rating/feedback interaction."""

    seq: int
    user: str
    item: str
    value: float
    event_time_ms: int


@dataclasses.dataclass(frozen=True)
class DeltaBatch:
    """A consumed slice of the pending delta: commit ``upto_seq`` once
    (and only once) the slice became a live model."""

    events: List[DeltaEvent]
    upto_seq: int
    oldest_event_ms: Optional[int]

    @property
    def user_ids(self) -> List[str]:
        return sorted({e.user for e in self.events})

    @property
    def item_ids(self) -> List[str]:
        return sorted({e.item for e in self.events})


class FeedWatcher:
    """Accumulates one app's rating delta off the changefeed.

    Thread contract: :meth:`poll` runs from one place at a time (the
    controller's tick); the cheap readers (:meth:`feed_lag`,
    :meth:`pending_count`, :meth:`oldest_pending_ms`) are safe from any
    thread — scrape-thread gauge callbacks included — because all shared
    state mutates under ``_lock`` while the feed fetch itself happens
    outside it (a slow primary must never block a metrics scrape)."""

    def __init__(
        self,
        feed,
        app_id: int,
        event_values: Dict[str, object],
        state_dir: str,
        batch_limit: int = 500,
        max_pending: int = 250_000,
    ):
        self._feed = feed
        self._app_id = int(app_id)
        self._event_values = dict(event_values)
        self._batch_limit = batch_limit
        self._max_pending = max_pending
        self._lock = threading.Lock()
        os.makedirs(state_dir, exist_ok=True)
        self._cursor_path = os.path.join(state_dir, CURSOR_NAME)
        self.cursor_seq = 0  # durable: last COMMITTED seq
        self.generation: Optional[str] = None
        self._load_cursor()
        #: in-memory read position (>= cursor_seq); re-derived from the
        #: durable cursor on restart, so an uncommitted suffix re-reads
        self.position = self.cursor_seq
        self.last_seq = self.cursor_seq  # feed head, as last observed
        self._pending: List[DeltaEvent] = []
        self.skipped_events = 0  # malformed/undecodable, counted not fatal
        #: optional per-event tap, called OUTSIDE the watcher lock for
        #: every freshly accepted delta event — the continuous controller
        #: wires it to the quality monitor's feedback join
        #: (docs/observability.md#quality). Exceptions are swallowed: an
        #: observer must never wedge the feed. A restart may replay the
        #: uncommitted suffix through the tap once (same contract as the
        #: fold itself: resumed, possibly re-observed, never lost).
        self.on_event = None
        #: counter hook the owner wires so a swallowed tap failure is
        #: counted, never invisible (docs/slo.md; obs-swallowed-observer)
        self.on_event_error = None
        #: stall-watchdog heartbeat hook (docs/slo.md): called once per
        #: poll round so a wedged fetch is attributable to the feed
        self.heartbeat = None

    # -- durable cursor ---------------------------------------------------
    def _load_cursor(self) -> None:
        try:
            with open(self._cursor_path) as fh:
                state = json.load(fh)
            self.cursor_seq = int(state["seq"])
            self.generation = state.get("generation")
        except (OSError, ValueError, KeyError):
            self.cursor_seq = 0
            self.generation = None

    def _persist_cursor(self) -> None:
        atomic_write_bytes(
            self._cursor_path,
            json.dumps(
                {"seq": self.cursor_seq, "generation": self.generation}
            ).encode(),
        )

    # -- op extraction ----------------------------------------------------
    def _extract(self, seq: int, op: dict, out: List[DeltaEvent]) -> None:
        kind = op.get("kind")
        if kind == "event_insert":
            if op.get("app") == self._app_id:
                self._extract_event(seq, op.get("event") or {}, out)
        elif kind == "event_write":
            if op.get("app") == self._app_id:
                for obj in op.get("events") or []:
                    self._extract_event(seq, obj, out)
        # deletes/metadata/models are invisible to fold-in by design: a
        # deleted rating only leaves the model at the next full retrain
        # (docs/continuous.md#failure-modes)

    def _extract_event(self, seq: int, obj: dict, out: List[DeltaEvent]) -> None:
        rule = self._event_values.get(obj.get("event"))
        if rule is None:
            return
        user = obj.get("entityId")
        item = obj.get("targetEntityId")
        if not user or not item:
            return
        try:
            if isinstance(rule, str):
                value = float((obj.get("properties") or {})[rule])
            else:
                value = float(rule)
            when = obj.get("eventTime")
            event_time_ms = to_millis(parse_event_time(when)) if when else 0
        except (KeyError, TypeError, ValueError):
            # a poison event must not wedge the loop forever; the full
            # retrain path reads through the store's own validation
            self.skipped_events += 1
            logger.debug("continuous: skipping undecodable event at seq %d", seq)
            return
        out.append(
            DeltaEvent(
                seq=seq, user=str(user), item=str(item), value=value,
                event_time_ms=event_time_ms,
            )
        )

    # -- tailing ----------------------------------------------------------
    def poll(self, max_rounds: int = 50) -> int:
        """Read the feed forward from ``position``, filtering matches into
        the pending delta. Returns how many delta events were added.
        Raises :class:`FeedGap` when incremental tailing is over."""
        added = 0
        if self.heartbeat is not None:
            self.heartbeat()
        for _ in range(max_rounds):
            with self._lock:
                since = self.position
                if len(self._pending) >= self._max_pending:
                    # bounded accumulation: beyond this the delta is no
                    # longer "incremental" anyway — the policy escalates
                    # on delta fraction; stop reading ahead rather than
                    # hold unbounded memory (feed_lag keeps growing, the
                    # obs signal that the loop is saturated)
                    return added
            batch = self._feed.fetch(since, self._batch_limit)  # no lock held
            generation = batch.get("generation")
            changes = batch.get("changes", [])
            fresh: List[DeltaEvent] = []
            top = since
            for entry in changes:
                seq = int(entry["seq"])
                if seq <= since:
                    continue
                top = max(top, seq)
                self._extract(seq, entry.get("op") or {}, fresh)
            with self._lock:
                if self.generation is None:
                    self.generation = generation
                elif generation is not None and generation != self.generation:
                    if self._is_continuation(batch):
                        # promoted-standby failover: the new log CONTINUES
                        # our numbering (its base_seq explicitly extends a
                        # predecessor and it can serve our position), so
                        # the cursor stays meaningful — adopt the new
                        # generation and resume WITHOUT replay or retrain
                        # (docs/continuous.md#per-partition-cursors)
                        logger.warning(
                            "continuous: feed generation %s -> %s is a "
                            "promoted continuation at seq %d; adopting",
                            self.generation, generation, self.position,
                        )
                        self.generation = generation
                    else:
                        raise FeedGap(
                            f"feed generation changed ({self.generation} "
                            f"-> {generation}): primary store replaced"
                        )
                self._pending.extend(fresh)
                self.position = max(self.position, top)
                self.last_seq = max(
                    self.position, int(batch.get("lastSeq", self.last_seq))
                )
                added += len(fresh)
                caught_up = not changes or self.position >= self.last_seq
            tap = self.on_event
            if tap is not None:
                for event in fresh:  # outside the lock: observer code
                    try:
                        tap(event)
                    except Exception:
                        if self.on_event_error is not None:
                            self.on_event_error()  # counted, not invisible
                        logger.debug(
                            "continuous: on_event tap failed", exc_info=True
                        )
            if caught_up:
                break
        return added

    def _is_continuation(self, batch: dict) -> bool:
        """Is a generation change a *promoted standby continuing the
        same history* rather than a wiped/replaced store? True when the
        new log (a) explicitly continues a predecessor's numbering
        (``oldestSeq > 1`` means nonzero base_seq), (b) can serve our
        position (``oldestSeq <= position + 1`` — no unreadable window
        between cursor and log start), and (c) has not rewound behind us
        (``lastSeq >= position``). A wiped store re-mints from seq 1 and
        fails (a); a promotion the watcher lagged behind fails (b) —
        both correctly stay a :class:`FeedGap`. Caller holds ``_lock``.
        """
        oldest = batch.get("oldestSeq")
        try:
            last = int(batch.get("lastSeq", -1))
            oldest = int(oldest) if oldest is not None else None
        except (TypeError, ValueError):
            return False
        return (
            oldest is not None
            and oldest > 1
            and oldest <= self.position + 1
            and last >= self.position
        )

    # -- introspection (gauge-callback safe) ------------------------------
    def feed_lag(self) -> int:
        """Ops between the read position and the feed head (the
        ``pio_continuous_feed_lag_ops`` gauge)."""
        with self._lock:
            return max(0, self.last_seq - self.position)

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def oldest_pending_ms(self) -> Optional[int]:
        """Event time of the oldest unfolded delta event (freshness
        accounting: model-live lag is measured from here)."""
        with self._lock:
            if not self._pending:
                return None
            return min(e.event_time_ms for e in self._pending)

    # -- consumption ------------------------------------------------------
    def take_batch(self) -> Optional[DeltaBatch]:
        """Snapshot the pending delta for one training cycle. The pending
        buffer is NOT cleared — :meth:`commit` clears it once the batch
        became a live model, so a failed/rolled-back cycle re-folds."""
        with self._lock:
            if not self._pending:
                return None
            events = list(self._pending)
            return DeltaBatch(
                events=events,
                upto_seq=max(self.position, events[-1].seq),
                oldest_event_ms=min(e.event_time_ms for e in events),
            )

    def commit(self, upto_seq: int) -> None:
        """Durably advance the cursor through ``upto_seq`` and drop the
        consumed delta. Call exactly when the batch's model went live."""
        upto_seq = int(upto_seq)  # JSON round-trips may deliver a str
        with self._lock:
            self._pending = [e for e in self._pending if e.seq > upto_seq]
            self.cursor_seq = max(self.cursor_seq, upto_seq)
            self._persist_cursor()

    def resync(self) -> None:
        """Post-gap recovery: jump the cursor to the feed head and drop
        the (incomplete) pending delta. Only call after a full retrain
        has covered the missed history."""
        ck = self._feed.checkpoint()
        with self._lock:
            self._pending = []
            self.cursor_seq = int(ck.get("seq", 0))
            self.position = self.cursor_seq
            self.last_seq = self.cursor_seq
            self.generation = ck.get("generation")
            self._persist_cursor()
        logger.warning(
            "continuous: feed resynced to seq %d (generation %s)",
            self.cursor_seq, self.generation,
        )


class PartitionedFeedWatcher:
    """N per-partition :class:`FeedWatcher` children behind the single-
    watcher surface the continuous controller drives
    (``docs/continuous.md#per-partition-cursors``).

    Each partition's changefeed is an independent history with its own
    **durable cursor** (``partition-<i>/continuous_cursor.json``) — there
    is no merged sequence space, so there is nothing a cross-partition
    commit could reorder or drop. The merged delta orders events by
    ``(event_time_ms, partition, seq)`` — deterministic for a given set
    of consumed ops regardless of poll interleaving, seq-ordered within
    each partition (what convergent folding needs).

    Failure scoping: a gap or non-continuation generation change on ONE
    partition marks only that partition gapped — the others keep
    accumulating (their cursors and uncommitted suffixes untouched) —
    and :meth:`poll` raises :class:`FeedGap` naming the gapped set so
    the controller escalates to a full retrain exactly as today.
    :meth:`resync` then jumps ONLY the gapped partitions to their feed
    heads; the healthy partitions resume their uncommitted suffixes.
    """

    def __init__(
        self,
        feeds,
        app_id: int,
        event_values: Dict[str, object],
        state_dir: str,
        batch_limit: int = 500,
        max_pending: int = 250_000,
    ):
        feeds = list(feeds)
        if not feeds:
            raise ValueError("PartitionedFeedWatcher needs >= 1 feed")
        self.watchers = [
            FeedWatcher(
                feed, app_id, event_values,
                os.path.join(state_dir, f"partition-{i}"),
                batch_limit=batch_limit,
                # each child bounds its own share: the merged pending
                # stays bounded by the same total as one flat watcher
                max_pending=max(1, max_pending // len(feeds)),
            )
            for i, feed in enumerate(feeds)
        ]
        self._lock = threading.Lock()
        #: partition indices whose feed gapped; cleared by resync()
        self._gapped: set = set()

    # -- observer hooks (fan to every child) ------------------------------
    @property
    def on_event(self):
        return self.watchers[0].on_event

    @on_event.setter
    def on_event(self, tap) -> None:
        for w in self.watchers:
            w.on_event = tap

    @property
    def on_event_error(self):
        return self.watchers[0].on_event_error

    @on_event_error.setter
    def on_event_error(self, hook) -> None:
        for w in self.watchers:
            w.on_event_error = hook

    @property
    def heartbeat(self):
        return self.watchers[0].heartbeat

    @heartbeat.setter
    def heartbeat(self, hook) -> None:
        for w in self.watchers:
            w.heartbeat = hook

    # -- tailing ----------------------------------------------------------
    def poll(self, max_rounds: int = 50) -> int:
        """Poll every non-gapped partition; a child's gap is recorded
        and the rest STILL poll (a dead partition must not starve the
        healthy keyspace), then one :class:`FeedGap` naming the gapped
        set raises — same escalation contract as the flat watcher."""
        added = 0
        errors = []
        with self._lock:
            gapped = set(self._gapped)
        for idx, w in enumerate(self.watchers):
            if idx in gapped:
                continue  # pointless until resync(); others keep flowing
            try:
                added += w.poll(max_rounds=max_rounds)
            except FeedGap as exc:
                gapped.add(idx)
                errors.append(f"partition {idx}: {exc}")
        with self._lock:
            self._gapped |= gapped
            gap_now = sorted(self._gapped)
        if gap_now:
            raise FeedGap(
                f"partition(s) {gap_now} gapped"
                + (f" ({'; '.join(errors)})" if errors else "")
            )
        return added

    # -- introspection (gauge-callback safe) ------------------------------
    def feed_lag(self) -> int:
        return sum(w.feed_lag() for w in self.watchers)

    def pending_count(self) -> int:
        return sum(w.pending_count() for w in self.watchers)

    def oldest_pending_ms(self) -> Optional[int]:
        values = [
            ms for ms in (w.oldest_pending_ms() for w in self.watchers)
            if ms is not None
        ]
        return min(values) if values else None

    @property
    def skipped_events(self) -> int:
        return sum(w.skipped_events for w in self.watchers)

    @property
    def cursor_seq(self) -> Dict[str, int]:
        """Per-partition durable cursors (status surface; the flat
        watcher's single int becomes one entry per partition)."""
        return {str(i): w.cursor_seq for i, w in enumerate(self.watchers)}

    @property
    def position(self) -> Dict[str, int]:
        return {str(i): w.position for i, w in enumerate(self.watchers)}

    # -- consumption -------------------------------------------------------
    def take_batch(self) -> Optional[DeltaBatch]:
        """Merged snapshot of every partition's pending delta.
        ``upto_seq`` is a per-partition map (JSON-safe string keys) —
        :meth:`commit` advances each durable cursor independently, so no
        partition's ack ever gates another's."""
        parts = [(i, w.take_batch()) for i, w in enumerate(self.watchers)]
        parts = [(i, b) for i, b in parts if b is not None]
        if not parts:
            return None
        decorated = [
            (e.event_time_ms, i, e.seq, e)
            for i, b in parts
            for e in b.events
        ]
        decorated.sort(key=lambda t: t[:3])
        return DeltaBatch(
            events=[t[3] for t in decorated],
            upto_seq={str(i): b.upto_seq for i, b in parts},
            oldest_event_ms=min(b.oldest_event_ms for _i, b in parts),
        )

    def take_batches(self) -> Optional[Dict[int, "DeltaBatch"]]:
        """Per-partition snapshots of the pending delta — the partitioned
        fold path's input (docs/continuous.md#partitioned-folds): the
        controller folds each partition's delta concurrently and commits
        ONLY the partitions whose fold completed, so a slow partition
        never gates another's cursor. Same non-clearing contract as
        :meth:`take_batch`: :meth:`commit` drops consumed events."""
        parts = {i: w.take_batch() for i, w in enumerate(self.watchers)}
        parts = {i: b for i, b in parts.items() if b is not None}
        return parts or None

    def commit(self, upto_seq) -> None:
        """Advance each partition's durable cursor through its own
        ``upto_seq`` entry (absent partitions had nothing in the batch
        and stay put). Accepts the JSON-round-tripped string-keyed map
        the controller persists."""
        if not isinstance(upto_seq, dict):
            raise TypeError(
                "PartitionedFeedWatcher.commit needs the per-partition "
                f"upto_seq map from take_batch(), got {type(upto_seq)}"
            )
        for key, seq in upto_seq.items():
            idx = int(key)
            if not (0 <= idx < len(self.watchers)):
                # a candidate that survived a partition-count change
                # (a resharding restart): commit what still exists, log
                # the rest — wedging the LIVE path would strand the
                # whole loop over an index that no longer has a cursor
                logger.warning(
                    "continuous: dropping commit for unknown partition "
                    "%s (now %d partitions)", key, len(self.watchers),
                )
                continue
            self.watchers[idx].commit(int(seq))

    def resync(self) -> None:
        """Partition-scoped post-gap recovery: ONLY the gapped
        partitions jump to their feed heads (dropping their incomplete
        deltas); the healthy partitions keep their cursors AND their
        uncommitted pending suffixes. With no recorded gap (a restart
        lost the in-memory set mid-gap-retrain) every partition resyncs
        — conservative, and safe: the full retrain that triggered the
        resync read the whole store."""
        with self._lock:
            gapped = sorted(self._gapped)
        targets = gapped or list(range(len(self.watchers)))
        for idx in targets:
            self.watchers[idx].resync()
        with self._lock:
            self._gapped.clear()


def handoff_cursors(new_feeds, state_dir: str) -> Dict[int, dict]:
    """Pre-seed the per-partition durable cursors at the NEW layout's
    feed heads — the live-migration cursor handoff
    (docs/storage.md#live-migration).

    Call AFTER the cutover flip, once the old-layout watcher is drained
    (caught up, every taken batch folded and committed) and retired. The
    watermark guarantees the new layout holds exactly the folded
    history, so a cursor at each new feed's head re-folds nothing (zero
    duplicates) and — because post-flip writes land only in the new
    layout at higher seqs — misses nothing. A :class:`PartitionedFeedWatcher`
    (or single :class:`FeedWatcher` for ``len(new_feeds) == 1``) built
    over ``state_dir`` afterwards resumes from these cursors as if it
    had tailed the new layout all along.

    Returns ``{partition index: written cursor}`` for status output.
    """
    new_feeds = list(new_feeds)
    written: Dict[int, dict] = {}
    for i, feed in enumerate(new_feeds):
        cp = feed.checkpoint()
        cursor = {
            "seq": int(cp.get("seq", cp.get("lastSeq", 0))),
            "generation": cp.get("generation"),
        }
        if len(new_feeds) == 1:
            cursor_dir = state_dir
        else:
            cursor_dir = os.path.join(state_dir, f"partition-{i}")
        os.makedirs(cursor_dir, exist_ok=True)
        atomic_write_bytes(
            os.path.join(cursor_dir, CURSOR_NAME),
            json.dumps(cursor).encode(),
        )
        written[i] = cursor
    return written


def make_watcher(
    feeds,
    app_id: int,
    event_values: Dict[str, object],
    state_dir: str,
    **kwargs,
):
    """One feed → :class:`FeedWatcher`; a list of per-partition feeds →
    :class:`PartitionedFeedWatcher`. The controller's one construction
    point for both shapes."""
    if isinstance(feeds, (list, tuple)):
        if len(feeds) == 1:
            return FeedWatcher(
                feeds[0], app_id, event_values, state_dir, **kwargs
            )
        return PartitionedFeedWatcher(
            list(feeds), app_id, event_values, state_dir, **kwargs
        )
    return FeedWatcher(feeds, app_id, event_values, state_dir, **kwargs)
