"""DASE component contracts: DataSource, Preparator, Algorithm, Serving.

Rebuild of the reference's controller base classes
(``core/src/main/scala/io/prediction/controller/{DataSource,Preparator,
Algorithm,Serving}.scala`` over the typeless ``core/Base*.scala`` layer).

The reference's P/L/P2L trichotomy (``Algorithm.scala:41-256``) — distributed
vs. local vs. distributed-train/local-model — was an artifact of RDD-based
execution. Here data and models are pytrees; *where* they live is a sharding
annotation, not a class hierarchy (SURVEY §7):

- a "P" model is a pytree of ``jax.Array`` s sharded over the workflow mesh;
- an "L" model is a replicated pytree (every device holds it);
- "P2L" is ``jax.device_get`` of sharded train output into host memory.

Algorithms declare how their trained model persists via the three-way
protocol the reference encodes in ``makeSerializableModels``
(``Engine.scala:254-272``): a :class:`PersistentModel` saves itself (analogue
of ``IPersistentModel``, ``IPersistentModel.scala:60-137``); a plain picklable
model is blobbed by the workflow (Kryo analogue); :data:`RETRAIN` opts out and
forces retraining at deploy (the ``Unit`` model of ``Algorithm.scala:80-101``).
"""

from __future__ import annotations

import abc
import inspect
from typing import (
    Any,
    Callable,
    Dict,
    Generic,
    List,
    Optional,
    Sequence,
    Tuple,
    Type,
    TypeVar,
)

from .params import EmptyParams, Params

TD = TypeVar("TD")  # training data
EI = TypeVar("EI")  # evaluation info
PD = TypeVar("PD")  # prepared data
M = TypeVar("M")  # model
Q = TypeVar("Q")  # query
P = TypeVar("P")  # predicted result
A = TypeVar("A")  # actual result


class _RetrainSentinel:
    """Marker: model not persisted; retrain at deploy (``Engine.scala:180``)."""

    def __repr__(self) -> str:
        return "RETRAIN"

    def __reduce__(self):
        # Pickle back to the module-level singleton so identity checks
        # survive blob-store roundtrips across processes.
        return (_retrain_instance, ())


def _retrain_instance() -> "_RetrainSentinel":
    return RETRAIN


#: Return this from ``make_persistent`` to request deploy-time retraining.
RETRAIN = _RetrainSentinel()


class SanityCheck(abc.ABC):
    """Optional hook run on data/models after each stage unless skipped
    (``controller/SanityCheck.scala``; invocation ``Engine.scala:526-582``)."""

    @abc.abstractmethod
    def sanity_check(self) -> None:
        """Raise on inconsistent data."""


def run_sanity_check(obj: Any, label: str) -> None:
    """Invoke ``sanity_check`` if the object opts in (duck-typed, like the
    reference's ``isInstanceOf[SanityCheck]`` test)."""
    check = getattr(obj, "sanity_check", None)
    if callable(check):
        check()


class Controller:
    """Common base: every DASE component holds its ``Params``
    (``controller/Params.scala:23``; instantiation via :func:`doer`)."""

    params: Params = EmptyParams()


def doer(cls: Type, params: Params) -> Any:
    """Instantiate a controller class with or without params.

    The ``Doer`` reflection constructor (``core/AbstractDoer.scala:30-53``):
    prefer a 1-arg ``(params)`` constructor, fall back to zero-arg.
    """
    try:
        sig = inspect.signature(cls.__init__)
        accepts_params = len(
            [
                p
                for name, p in sig.parameters.items()
                if name != "self"
                and p.kind
                in (p.POSITIONAL_OR_KEYWORD, p.POSITIONAL_ONLY)
                and p.default is p.empty
            ]
        ) >= 1 or "params" in sig.parameters
    except (TypeError, ValueError):
        accepts_params = False
    if accepts_params:
        instance = cls(params)
    else:
        instance = cls()
        instance.params = params
    if getattr(instance, "params", None) is None:
        instance.params = params
    return instance


class DataSource(Controller, Generic[TD, EI, Q, A]):
    """Reads training and evaluation data
    (``controller/DataSource.scala:38-107``)."""

    def read_training(self, ctx) -> TD:
        """Training path (``PDataSource.readTraining``)."""
        raise NotImplementedError

    def read_eval(self, ctx) -> List[Tuple[TD, EI, List[Tuple[Q, A]]]]:
        """Evaluation path: (train split, eval info, (query, actual) set) per
        fold (``PDataSource.readEval``, ``DataSource.scala:48-56``)."""
        return []


class Preparator(Controller, Generic[TD, PD]):
    """Transforms training data for algorithms
    (``controller/Preparator.scala:38-74``)."""

    def prepare(self, ctx, training_data: TD) -> PD:
        raise NotImplementedError


class IdentityPreparator(Preparator[TD, TD]):
    """Pass-through (``controller/IdentityPreparator`` in
    ``Preparator.scala:76-96``)."""

    def prepare(self, ctx, training_data: TD) -> TD:
        return training_data


class Algorithm(Controller, Generic[PD, M, Q, P]):
    """Train + predict (``controller/Algorithm.scala``).

    ``batch_predict`` is the evaluation path (``batchPredict``,
    ``Algorithm.scala:60-78``); the default maps ``predict`` but TPU
    algorithms override it with a single vectorized device call.
    """

    def train(self, ctx, prepared_data: PD) -> M:
        raise NotImplementedError

    def predict(self, model: M, query: Q) -> P:
        raise NotImplementedError

    def batch_predict(
        self, model: M, indexed_queries: Sequence[Tuple[int, Q]]
    ) -> List[Tuple[int, P]]:
        return [(i, self.predict(model, q)) for i, q in indexed_queries]

    # -- persistence protocol (Engine.scala:254-272) ----------------------
    def make_persistent(self, instance_id: str, model: M, ctx) -> Any:
        """Decide how the trained model persists.

        Return value semantics:

        - a :class:`PersistentModel` instance → it saved itself; a manifest
          with its class path is stored instead of the model bytes;
        - :data:`RETRAIN` → nothing persisted, deploy retrains;
        - anything else → pickled into the model blob store by the workflow.
        """
        if isinstance(model, PersistentModel):
            # pio: lint-ok[robust-nonatomic-checkpoint] delegation, not a write: the PersistentModel subclass owns the file I/O and is linted where it is defined
            if model.save(instance_id, self.params, ctx):
                return PersistentModelManifest.of(model)
            return RETRAIN
        return model

    def query_class(self) -> Optional[Type[Q]]:
        """Query dataclass for JSON decoding at the query server (the
        analogue of the per-algo ``querySerializer``,
        ``CreateServer.scala:475-478``)."""
        return None


class PersistentModel(abc.ABC):
    """Self-persisting model (``IPersistentModel.scala:60-96``).

    Implementations also provide a ``load`` classmethod (the
    ``IPersistentModelLoader`` companion, ``IPersistentModel.scala:98-117``).
    """

    @abc.abstractmethod
    def save(self, instance_id: str, params: Params, ctx) -> bool:
        """Persist; return False to fall back to deploy-time retraining."""

    @classmethod
    @abc.abstractmethod
    def load(cls, instance_id: str, params: Params, ctx) -> "PersistentModel":
        ...


class PersistentModelManifest:
    """Records the class path of a self-persisted model
    (``workflow/PersistentModelManifest.scala``)."""

    def __init__(self, class_path: str):
        self.class_path = class_path

    @staticmethod
    def of(model: PersistentModel) -> "PersistentModelManifest":
        cls = type(model)
        return PersistentModelManifest(f"{cls.__module__}:{cls.__qualname__}")

    def resolve(self) -> Type[PersistentModel]:
        import importlib

        module_name, _, qualname = self.class_path.partition(":")
        obj: Any = importlib.import_module(module_name)
        for part in qualname.split("."):
            obj = getattr(obj, part)
        return obj

    def __repr__(self) -> str:
        return f"PersistentModelManifest({self.class_path!r})"

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, PersistentModelManifest)
            and self.class_path == other.class_path
        )


class Serving(Controller, Generic[Q, P]):
    """Combines per-algorithm predictions into one response
    (``controller/Serving.scala:34-60``)."""

    def serve(self, query: Q, predictions: Sequence[P]) -> P:
        raise NotImplementedError

    def supplement(self, query: Q) -> Q:
        """Pre-predict query enrichment hook (``Serving.scala`` supplement)."""
        return query


class FirstServing(Serving[Q, P]):
    """Returns the first algorithm's prediction (``LFirstServing``,
    ``Serving.scala:62-81``)."""

    def serve(self, query: Q, predictions: Sequence[P]) -> P:
        return predictions[0]


class AverageServing(Serving[Q, float]):
    """Averages numeric predictions (``LAverageServing``,
    ``Serving.scala:83-102``)."""

    def serve(self, query: Q, predictions: Sequence[float]) -> float:
        return sum(predictions) / len(predictions)
