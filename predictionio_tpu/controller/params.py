"""Params: typed component parameters parsed from engine-variant JSON.

Rebuild of the reference's ``Params`` marker + reflective JSON extraction
(``core/src/main/scala/io/prediction/controller/Params.scala:23-43`` and
``workflow/WorkflowUtils.scala:130-209`` ``extractParams``): user parameter
classes are plain dataclasses; :func:`extract_params` converts the
``{name, params}`` blocks of an ``engine.json`` variant into instances by
field-name matching — the explicit-registry replacement for Scala
ctor-arg reflection (SURVEY §7 "typeless/typed boundary").
"""

from __future__ import annotations

import dataclasses
import types
import typing
from typing import Any, Dict, Mapping, Optional, Type, TypeVar

T = TypeVar("T")

_UNION_TYPES = (typing.Union, getattr(types, "UnionType", typing.Union))


class ParamsError(ValueError):
    """Raised when JSON cannot be converted into the target Params class."""


@dataclasses.dataclass(frozen=True)
class Params:
    """Base class for all component parameters (``Params.scala:23-33``).

    Subclasses are frozen dataclasses; fields define the accepted JSON keys.
    """


@dataclasses.dataclass(frozen=True)
class EmptyParams(Params):
    """No parameters (``Params.scala:38-43``)."""


def _convert(value: Any, annotation: Any, where: str) -> Any:
    """Best-effort conversion of a JSON value to an annotated field type."""
    if annotation is Any or annotation is dataclasses.MISSING:
        return value
    origin = typing.get_origin(annotation)
    if origin in _UNION_TYPES:  # Optional[...], Union[...], and PEP 604 X | Y
        args = typing.get_args(annotation)
        if value is None and type(None) in args:
            return None
        errors = []
        for arg in args:
            if arg is type(None):
                continue
            try:
                return _convert(value, arg, where)
            except ParamsError as exc:
                errors.append(str(exc))
        raise ParamsError(
            f"{where}: {value!r} matches no member of {annotation}"
            + (f" ({'; '.join(errors)})" if errors else "")
        )
    if origin in (list, tuple):
        if not isinstance(value, (list, tuple)):
            raise ParamsError(f"{where}: expected a list, got {type(value).__name__}")
        args = typing.get_args(annotation)
        elem = args[0] if args else Any
        converted = [
            _convert(v, elem, f"{where}[{i}]") for i, v in enumerate(value)
        ]
        return tuple(converted) if origin is tuple else converted
    if origin is dict:
        if not isinstance(value, Mapping):
            raise ParamsError(f"{where}: expected an object, got {type(value).__name__}")
        args = typing.get_args(annotation)
        vt = args[1] if len(args) == 2 else Any
        return {k: _convert(v, vt, f"{where}.{k}") for k, v in value.items()}
    if dataclasses.is_dataclass(annotation) and isinstance(annotation, type):
        if not isinstance(value, Mapping):
            raise ParamsError(
                f"{where}: expected an object for {annotation.__name__}"
            )
        return extract_params(annotation, value)
    if annotation is float:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise ParamsError(f"{where}: expected a number, got {value!r}")
        return float(value)
    if annotation is int:
        if isinstance(value, bool) or not isinstance(value, int):
            raise ParamsError(f"{where}: expected an integer, got {value!r}")
        return value
    if annotation is bool:
        if not isinstance(value, bool):
            raise ParamsError(f"{where}: expected a boolean, got {value!r}")
        return value
    if annotation is str:
        if not isinstance(value, str):
            raise ParamsError(f"{where}: expected a string, got {value!r}")
        return value
    return value  # unconstrained annotation: pass through


def extract_params(cls: Type[T], json_value: Optional[Mapping[str, Any]]) -> T:
    """JSON object → dataclass instance (``WorkflowUtils.extractParams``).

    Unknown keys are rejected (the reference fails on ctor mismatch); missing
    keys fall back to dataclass defaults, and a missing required key raises.
    """
    if not dataclasses.is_dataclass(cls):
        raise ParamsError(f"{cls!r} is not a dataclass Params type")
    data = dict(json_value or {})
    hints = typing.get_type_hints(cls)
    kwargs: Dict[str, Any] = {}
    field_names = {f.name for f in dataclasses.fields(cls)}
    unknown = set(data) - field_names
    if unknown:
        raise ParamsError(
            f"Unable to extract {cls.__name__}: unknown fields {sorted(unknown)}"
        )
    for f in dataclasses.fields(cls):
        if f.name in data:
            kwargs[f.name] = _convert(
                data[f.name], hints.get(f.name, Any), f"{cls.__name__}.{f.name}"
            )
        elif (
            f.default is dataclasses.MISSING
            and f.default_factory is dataclasses.MISSING  # type: ignore[misc]
        ):
            raise ParamsError(
                f"Unable to extract {cls.__name__}: missing required field "
                f"{f.name!r}"
            )
    try:
        return cls(**kwargs)  # type: ignore[return-value]
    except (TypeError, ValueError) as exc:
        raise ParamsError(f"Unable to construct {cls.__name__}: {exc}") from exc


def params_to_json(params: Any) -> Dict[str, Any]:
    """Dataclass instance → JSON dict (inverse of :func:`extract_params`)."""
    if dataclasses.is_dataclass(params) and not isinstance(params, type):
        return {
            f.name: _value_to_json(getattr(params, f.name))
            for f in dataclasses.fields(params)
        }
    raise ParamsError(f"{params!r} is not a Params dataclass instance")


def _value_to_json(value: Any) -> Any:
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return params_to_json(value)
    if isinstance(value, (list, tuple)):
        return [_value_to_json(v) for v in value]
    if isinstance(value, dict):
        return {k: _value_to_json(v) for k, v in value.items()}
    return value
