"""Evaluation: couples an engine with an evaluator; hyperparameter grids.

Rebuild of ``core/src/main/scala/io/prediction/controller/Evaluation.scala:59-124``
and ``Engine.scala:698-714`` (``EngineParamsGenerator``): an ``Evaluation``
names the engine + evaluator pair a ``pio eval`` run uses, and a generator
supplies the candidate EngineParams grid.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from .engine import Engine, EngineParams
from .metrics import Metric, MetricEvaluator


class Evaluation:
    """Subclass and set ``engine_metric`` (sugar building a MetricEvaluator,
    ``Evaluation.scala:93-116``) or ``engine_evaluator`` directly."""

    def __init__(self):
        self._engine: Optional[Engine] = None
        self._evaluator: Optional[MetricEvaluator] = None

    # -- engineEvaluator (Evaluation.scala:66-80) -------------------------
    @property
    def engine_evaluator(self) -> Tuple[Engine, MetricEvaluator]:
        if self._engine is None or self._evaluator is None:
            raise ValueError(
                "Evaluation has no engine/evaluator; set engine_metric or "
                "engine_evaluator first."
            )
        return (self._engine, self._evaluator)

    @engine_evaluator.setter
    def engine_evaluator(self, pair: Tuple[Engine, MetricEvaluator]) -> None:
        self._engine, self._evaluator = pair

    # -- engineMetric sugar (Evaluation.scala:93-116) ---------------------
    @property
    def engine_metric(self) -> Tuple[Engine, Metric]:
        raise NotImplementedError("engine_metric is write-only")

    @engine_metric.setter
    def engine_metric(self, pair: Tuple[Engine, Metric]) -> None:
        engine, metric = pair
        self.engine_evaluator = (engine, MetricEvaluator(metric))

    @property
    def engine_metrics(self):
        raise NotImplementedError("engine_metrics is write-only")

    @engine_metrics.setter
    def engine_metrics(
        self, triple: Tuple[Engine, Metric, Sequence[Metric]]
    ) -> None:
        engine, metric, others = triple
        self.engine_evaluator = (engine, MetricEvaluator(metric, others))

    @property
    def engine(self) -> Engine:
        return self.engine_evaluator[0]

    @property
    def evaluator(self) -> MetricEvaluator:
        return self.engine_evaluator[1]


class EngineParamsGenerator:
    """Supplies the hyperparameter grid (``Engine.scala:698-714``)."""

    def __init__(self, engine_params_list: Sequence[EngineParams] = ()):
        self._list: Optional[Sequence[EngineParams]] = (
            tuple(engine_params_list) if engine_params_list else None
        )

    @property
    def engine_params_list(self) -> Sequence[EngineParams]:
        if self._list is None:
            raise ValueError("engine_params_list is empty")
        return self._list

    @engine_params_list.setter
    def engine_params_list(self, value: Sequence[EngineParams]) -> None:
        self._list = tuple(value)
