"""Metrics and the metric evaluator.

Rebuild of ``core/src/main/scala/io/prediction/controller/Metric.scala:35-160``
and ``MetricEvaluator.scala:55-241``: metrics score the (query, prediction,
actual) sets an evaluation produces; the evaluator scores every candidate
EngineParams, picks the best by the metric's ordering, and can write the
winning variant JSON (``best.json`` parity).

TPU note: ``AverageMetric``-style per-tuple scores are exposed through
:meth:`Metric.calculate_batch` so subclasses may compute scores with one jit'd
device call over stacked arrays instead of a Python loop.
"""

from __future__ import annotations

import dataclasses
import json
import logging
from typing import Any, Generic, List, Optional, Sequence, Tuple, TypeVar

from ..utils.durability import atomic_write_bytes
from .engine import EngineParams, params_to_json

logger = logging.getLogger(__name__)

EI = TypeVar("EI")
Q = TypeVar("Q")
P = TypeVar("P")
A = TypeVar("A")
R = TypeVar("R")

#: evaluation output: per engine-params, per fold, the (Q, P, A) set
EvalDataSet = Sequence[Tuple[EI, Sequence[Tuple[Q, P, A]]]]


class Metric(Generic[EI, Q, P, A, R]):
    """Scores one evaluation data set (``Metric.scala:35-45``)."""

    @property
    def header(self) -> str:
        return type(self).__name__

    def calculate(self, ctx, eval_data_set: EvalDataSet) -> R:
        raise NotImplementedError

    def compare(self, r0: R, r1: R) -> int:
        """Ordering on results; larger is better by default."""
        if r0 == r1:
            return 0
        return 1 if r0 > r1 else -1  # type: ignore[operator]

    def __str__(self) -> str:
        return self.header


class AverageMetric(Metric[EI, Q, P, A, float]):
    """Global average of per-tuple scores (``Metric.scala:56-76``)."""

    def calculate_point(self, q: Q, p: P, a: A) -> float:
        raise NotImplementedError

    def calculate(self, ctx, eval_data_set: EvalDataSet) -> float:
        total, count = 0.0, 0
        for _, qpa in eval_data_set:
            for q, p, a in qpa:
                total += self.calculate_point(q, p, a)
                count += 1
        return total / count if count else float("-inf")


class OptionAverageMetric(Metric[EI, Q, P, A, float]):
    """Average of non-None per-tuple scores; -inf when none
    (``Metric.scala:87-120``)."""

    def calculate_point(self, q: Q, p: P, a: A) -> Optional[float]:
        raise NotImplementedError

    def calculate(self, ctx, eval_data_set: EvalDataSet) -> float:
        total, count = 0.0, 0
        for _, qpa in eval_data_set:
            for q, p, a in qpa:
                score = self.calculate_point(q, p, a)
                if score is not None:
                    total += score
                    count += 1
        return total / count if count else float("-inf")


class SumMetric(Metric[EI, Q, P, A, float]):
    """Global sum of per-tuple scores (``Metric.scala:122-142``)."""

    def calculate_point(self, q: Q, p: P, a: A) -> float:
        raise NotImplementedError

    def calculate(self, ctx, eval_data_set: EvalDataSet) -> float:
        return sum(
            self.calculate_point(q, p, a)
            for _, qpa in eval_data_set
            for q, p, a in qpa
        )


class ZeroMetric(Metric[EI, Q, P, A, float]):
    """Always 0 (``Metric.scala:144-152``) — placeholder metric."""

    def calculate(self, ctx, eval_data_set: EvalDataSet) -> float:
        return 0.0


@dataclasses.dataclass(frozen=True)
class MetricScores(Generic[R]):
    """Primary + other metric scores for one EngineParams
    (``MetricEvaluator.scala:43-53``)."""

    score: R
    other_scores: Tuple[Any, ...] = ()


@dataclasses.dataclass(frozen=True)
class MetricEvaluatorResult(Generic[R]):
    """Sweep outcome (``MetricEvaluator.scala:55-107``)."""

    best_score: MetricScores[R]
    best_engine_params: EngineParams
    best_idx: int
    metric_header: str
    other_metric_headers: Tuple[str, ...]
    engine_params_scores: Tuple[Tuple[EngineParams, MetricScores[R]], ...]
    output_path: Optional[str] = None

    def one_liner(self) -> str:
        return f"[{self.best_score.score}] {self.metric_header}"

    def to_json(self) -> str:
        return json.dumps(
            {
                "metricHeader": self.metric_header,
                "bestScore": _json_safe(self.best_score.score),
                "bestIdx": self.best_idx,
                "bestEngineParams": _engine_params_json(self.best_engine_params),
                "otherMetricHeaders": list(self.other_metric_headers),
                "scores": [
                    {
                        "engineParams": _engine_params_json(ep),
                        "score": _json_safe(ms.score),
                        "otherScores": [_json_safe(s) for s in ms.other_scores],
                    }
                    for ep, ms in self.engine_params_scores
                ],
            },
            indent=2,
        )

    def to_html(self) -> str:
        rows = "\n".join(
            f"<tr><td>{i}</td><td>{_json_safe(ms.score)}</td>"
            f"<td><pre>{json.dumps(_engine_params_json(ep), indent=1)}</pre></td></tr>"
            for i, (ep, ms) in enumerate(self.engine_params_scores)
        )
        return (
            f"<html><body><h1>{self.metric_header}</h1>"
            f"<p>Best score: {_json_safe(self.best_score.score)} "
            f"(iteration {self.best_idx})</p>"
            f"<table border=1><tr><th>#</th><th>score</th><th>params</th></tr>"
            f"{rows}</table></body></html>"
        )


def _json_safe(value: Any) -> Any:
    if isinstance(value, float):
        if value != value or value in (float("inf"), float("-inf")):
            return str(value)
    try:
        json.dumps(value)
        return value
    except (TypeError, ValueError):
        return repr(value)


def _engine_params_json(ep: EngineParams) -> dict:
    """EngineParams → engine-variant-shaped JSON (``MetricEvaluator``'s
    ``EngineVariant``, ``MetricEvaluator.scala:120-158``)."""
    def name_params(pair):
        name, params = pair
        return {"name": name, "params": params_to_json(params)}

    return {
        "datasource": name_params(ep.data_source_params),
        "preparator": name_params(ep.preparator_params),
        "algorithms": [name_params(p) for p in ep.algorithm_params_list],
        "serving": name_params(ep.serving_params),
    }


class MetricEvaluator(Generic[EI, Q, P, A, R]):
    """Scores every EngineParams and selects the max
    (``MetricEvaluator.scala:163-241``)."""

    def __init__(
        self,
        metric: Metric[EI, Q, P, A, R],
        other_metrics: Sequence[Metric[EI, Q, P, A, Any]] = (),
        output_path: Optional[str] = None,
    ):
        self.metric = metric
        self.other_metrics = tuple(other_metrics)
        self.output_path = output_path

    def evaluate_base(
        self,
        ctx,
        evaluation,
        engine_eval_data_set: Sequence[Tuple[EngineParams, EvalDataSet]],
        workflow_params=None,
        parallelism: int = 0,
    ) -> MetricEvaluatorResult[R]:
        def score_one(pair) -> Tuple[EngineParams, MetricScores[R]]:
            ep, eval_data_set = pair
            return ep, MetricScores(
                score=self.metric.calculate(ctx, eval_data_set),
                other_scores=tuple(
                    m.calculate(ctx, eval_data_set) for m in self.other_metrics
                ),
            )

        # Concurrent candidate scoring — the reference scores with a
        # parallel collection (``MetricEvaluator.scala:202-211``, ``.par``).
        # Metrics must be thread-safe across candidates (they are in the
        # reference for the same reason); jit'd batch metrics release the
        # GIL during device work.
        # scoring is host-bound: cap the pool regardless of how wide the
        # sweep itself ran (the mesh carried the sweep; threads carry this)
        n = min(parallelism if parallelism > 0 else 8,
                8, len(engine_eval_data_set))
        if n > 1 and len(engine_eval_data_set) > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                max_workers=n, thread_name_prefix="metric"
            ) as pool:
                scored: List[Tuple[EngineParams, MetricScores[R]]] = list(
                    pool.map(score_one, engine_eval_data_set)
                )
        else:
            scored = [score_one(pair) for pair in engine_eval_data_set]
        for idx, (ep, r) in enumerate(scored):
            logger.info("Iteration %d: score %s", idx, r.score)

        best_idx = 0
        for idx in range(1, len(scored)):
            # strict > keeps the earliest best, matching reduce with >= 0
            if self.metric.compare(scored[idx][1].score, scored[best_idx][1].score) > 0:
                best_idx = idx
        best_ep, best_scores = scored[best_idx]

        if self.output_path:
            self._save_engine_json(evaluation, best_ep, self.output_path)

        return MetricEvaluatorResult(
            best_score=best_scores,
            best_engine_params=best_ep,
            best_idx=best_idx,
            metric_header=self.metric.header,
            other_metric_headers=tuple(m.header for m in self.other_metrics),
            engine_params_scores=tuple(scored),
            output_path=self.output_path,
        )

    def _save_engine_json(
        self, evaluation, engine_params: EngineParams, path: str
    ) -> None:
        """Write the winning variant (``saveEngineJson``,
        ``MetricEvaluator.scala:169-191``)."""
        factory = type(evaluation).__name__ if evaluation is not None else ""
        variant = {
            "id": factory,
            "description": "",
            "engineFactory": factory,
            **_engine_params_json(engine_params),
        }
        atomic_write_bytes(path, json.dumps(variant, indent=2).encode("utf-8"))
        logger.info("Best variant params written to %s", path)
