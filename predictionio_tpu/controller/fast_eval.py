"""FastEvalEngine: prefix-memoized hyperparameter sweeps.

Rebuild of ``core/src/main/scala/io/prediction/controller/FastEvalEngine.scala:52-344``:
when sweeping a grid where only the later DASE stages vary, earlier stage
results are cached keyed by the *params prefix* — a sweep over algorithm
params reads and prepares data exactly once.

Caches use value equality on params (``FastEvalEngine.scala:299-302``). A
params class without value ``__eq__`` (i.e. not a dataclass) falls back to
identity and never hits the cache across distinct instances — the reference's
"not cached when isEqual not implemented" behavior
(``FastEvalEngineTest.scala:146``).

Trade-off carried over from the reference: FastEvalEngine caches *predictions
per algorithm-params prefix*, so serving-params-only sweeps reuse everything
upstream.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import defaultdict
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, Generic, List, Optional, Sequence, Tuple, TypeVar

from .dase import doer
from .engine import Engine, EngineParams, WorkflowParams
from .params import Params

K = TypeVar("K")
V = TypeVar("V")


class AssocCache(Generic[K, V]):
    """Equality-keyed cache (no hashability requirement on params) with
    exactly-once compute under concurrency.

    Parallel sweeps (mesh-sliced ``batch_eval``) hit these caches from
    several threads; the memoization-count contract
    (``FastEvalEngineTest.scala:30-146``: DataSource read exactly once per
    distinct prefix) must survive that. ``get_or_compute`` registers an
    in-flight Future under the lock, so a second thread asking for the
    same prefix blocks on the first thread's result instead of
    re-invoking the component."""

    def __init__(self):
        self._items: List[Tuple[K, Future]] = []
        self._lock = threading.Lock()

    def get(self, key: K) -> Optional[V]:
        with self._lock:
            found = next((fut for k, fut in self._items if k == key), None)
        return found.result() if found is not None else None  # wait unlocked

    def put(self, key: K, value: V) -> None:
        fut: Future = Future()
        fut.set_result(value)
        with self._lock:
            self._items.append((key, fut))

    def get_or_compute(self, key: K, compute: Callable[[], V]) -> V:
        with self._lock:
            for k, fut in self._items:
                if k == key:
                    found: Optional[Future] = fut
                    break
            else:
                found = None
                mine: Future = Future()
                self._items.append((key, mine))
        if found is not None:
            return found.result()  # blocks if another thread is computing
        try:
            value = compute()
        except BaseException as exc:
            mine.set_exception(exc)
            with self._lock:  # failed computes are not cached
                self._items.remove((key, mine))
            raise
        mine.set_result(value)
        return value

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


# Prefix keys (FastEvalEngine.scala:52-87)
@dataclasses.dataclass(frozen=True)
class DataSourcePrefix:
    data_source_params: Tuple[str, Params]


@dataclasses.dataclass(frozen=True)
class PreparatorPrefix:
    data_source_params: Tuple[str, Params]
    preparator_params: Tuple[str, Params]


@dataclasses.dataclass(frozen=True)
class AlgorithmsPrefix:
    data_source_params: Tuple[str, Params]
    preparator_params: Tuple[str, Params]
    algorithm_params_list: Tuple[Tuple[str, Params], ...]


@dataclasses.dataclass(frozen=True)
class ServingPrefix:
    data_source_params: Tuple[str, Params]
    preparator_params: Tuple[str, Params]
    algorithm_params_list: Tuple[Tuple[str, Params], ...]
    serving_params: Tuple[str, Params]


class FastEvalEngineWorkflow:
    """Holds the per-sweep caches (``FastEvalEngineWorkflow``,
    ``FastEvalEngine.scala:89-344``)."""

    def __init__(
        self,
        engine: "FastEvalEngine",
        ctx,
        workflow_params: WorkflowParams,
        train_slices=None,
    ):
        self.engine = engine
        self.ctx = ctx
        self.workflow_params = workflow_params
        #: optional SlicePool: the training stage (the device-heavy one)
        #: checks out a free mesh slice per distinct algorithms-prefix, so
        #: concurrent trainings run on disjoint devices. Only that one
        #: stage acquires — nested acquisition would deadlock, and the
        #: other stages are host-bound.
        self._train_slices = train_slices
        # caches (FastEvalEngine.scala:299-302)
        self.data_source_cache: AssocCache = AssocCache()
        self.preparator_cache: AssocCache = AssocCache()
        self.algorithms_cache: AssocCache = AssocCache()
        self.serving_cache: AssocCache = AssocCache()

    # each stage: compute through the previous stage's cached result,
    # exactly once per distinct prefix even under concurrent sweeps
    def get_data_source_result(self, prefix: DataSourcePrefix):
        def compute():
            name, params = prefix.data_source_params
            data_source = doer(self.engine.data_source_class_map[name], params)
            return data_source.read_eval(self.ctx)

        return self.data_source_cache.get_or_compute(prefix, compute)

    def get_preparator_result(self, prefix: PreparatorPrefix):
        def compute():
            eval_sets = self.get_data_source_result(
                DataSourcePrefix(prefix.data_source_params)
            )
            name, params = prefix.preparator_params
            preparator = doer(self.engine.preparator_class_map[name], params)
            return [
                (preparator.prepare(self.ctx, td), ei, qa)
                for td, ei, qa in eval_sets
            ]

        return self.preparator_cache.get_or_compute(prefix, compute)

    def get_algorithms_result(self, prefix: AlgorithmsPrefix):
        """Per fold: list over algos of indexed predictions
        (``computeAlgorithmsResult``, ``FastEvalEngine.scala:170-242``)."""

        def compute_with(ctx):
            prepared_sets = self.get_preparator_result(
                PreparatorPrefix(
                    prefix.data_source_params, prefix.preparator_params
                )
            )
            algos = [
                doer(self.engine.algorithm_class_map[name], params)
                for name, params in prefix.algorithm_params_list
            ]
            out = []
            for pd, ei, qa in prepared_sets:
                models = [a.train(ctx, pd) for a in algos]
                indexed = list(enumerate(q for q, _ in qa))
                per_algo = [
                    a.batch_predict(m, indexed)
                    for a, m in zip(algos, models)
                ]
                out.append((per_algo, ei, qa))
            return out

        def compute():
            if self._train_slices is not None:
                with self._train_slices.acquire() as sliced:
                    return compute_with(sliced)
            return compute_with(self.ctx)

        return self.algorithms_cache.get_or_compute(prefix, compute)

    def get_serving_result(self, prefix: ServingPrefix):
        def compute():
            algo_sets = self.get_algorithms_result(
                AlgorithmsPrefix(
                    prefix.data_source_params,
                    prefix.preparator_params,
                    prefix.algorithm_params_list,
                )
            )
            name, params = prefix.serving_params
            serving = doer(self.engine.serving_class_map[name], params)
            out = []
            for per_algo, ei, qa in algo_sets:
                by_query: Dict[int, Dict[int, Any]] = defaultdict(dict)
                for ai, indexed_preds in enumerate(per_algo):
                    for qi, p in indexed_preds:
                        by_query[qi][ai] = p
                qpa = []
                for qi, (q, a) in enumerate(qa):
                    preds = by_query.get(qi, {})
                    ordered = [preds[ai] for ai in sorted(preds)]
                    qpa.append((q, serving.serve(q, ordered), a))
                out.append((ei, qpa))
            return out

        return self.serving_cache.get_or_compute(prefix, compute)


class FastEvalEngine(Engine):
    """Engine whose ``batch_eval`` memoizes by params prefix
    (``FastEvalEngine.scala:310-344``)."""

    def batch_eval(
        self,
        ctx,
        engine_params_list: Sequence[EngineParams],
        workflow_params: WorkflowParams = WorkflowParams(),
        parallelism: int = 1,
    ):
        """Memoized sweep; ``parallelism > 1`` evaluates candidates
        concurrently on independent mesh slices while the exactly-once
        caches keep the invocation counts identical to a serial sweep
        (``FastEvalEngineTest.scala:30-146`` semantics)."""
        prefixes = [
            ServingPrefix(
                ep.data_source_params,
                ep.preparator_params,
                tuple(ep.algorithm_params_list),
                ep.serving_params,
            )
            for ep in engine_params_list
        ]
        if parallelism > 1 and len(engine_params_list) > 1:
            from ..parallel.sweep import SlicePool

            # Candidates run concurrently; the training stage checks a
            # free slice out of the pool per distinct algorithms-prefix,
            # so disjoint devices carry the concurrent trains while the
            # exactly-once caches keep invocation counts serial-identical.
            pool = SlicePool(ctx, parallelism)
            workflow = FastEvalEngineWorkflow(
                self, ctx, workflow_params, train_slices=pool
            )
            with ThreadPoolExecutor(
                max_workers=pool.n_slices, thread_name_prefix="sweep"
            ) as executor:
                futs = [
                    executor.submit(workflow.get_serving_result, p)
                    for p in prefixes
                ]
                return [
                    (ep, fut.result())
                    for ep, fut in zip(engine_params_list, futs)
                ]
        workflow = FastEvalEngineWorkflow(self, ctx, workflow_params)
        return [
            (ep, workflow.get_serving_result(p))
            for ep, p in zip(engine_params_list, prefixes)
        ]
