"""FastEvalEngine: prefix-memoized hyperparameter sweeps.

Rebuild of ``core/src/main/scala/io/prediction/controller/FastEvalEngine.scala:52-344``:
when sweeping a grid where only the later DASE stages vary, earlier stage
results are cached keyed by the *params prefix* — a sweep over algorithm
params reads and prepares data exactly once.

Caches use value equality on params (``FastEvalEngine.scala:299-302``). A
params class without value ``__eq__`` (i.e. not a dataclass) falls back to
identity and never hits the cache across distinct instances — the reference's
"not cached when isEqual not implemented" behavior
(``FastEvalEngineTest.scala:146``).

Trade-off carried over from the reference: FastEvalEngine caches *predictions
per algorithm-params prefix*, so serving-params-only sweeps reuse everything
upstream.
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import Any, Dict, Generic, List, Optional, Sequence, Tuple, TypeVar

from .dase import doer
from .engine import Engine, EngineParams, WorkflowParams
from .params import Params

K = TypeVar("K")
V = TypeVar("V")


class AssocCache(Generic[K, V]):
    """Equality-keyed cache (no hashability requirement on params)."""

    def __init__(self):
        self._items: List[Tuple[K, V]] = []

    def get(self, key: K) -> Optional[V]:
        for k, v in self._items:
            if k == key:
                return v
        return None

    def put(self, key: K, value: V) -> None:
        self._items.append((key, value))

    def __len__(self) -> int:
        return len(self._items)


# Prefix keys (FastEvalEngine.scala:52-87)
@dataclasses.dataclass(frozen=True)
class DataSourcePrefix:
    data_source_params: Tuple[str, Params]


@dataclasses.dataclass(frozen=True)
class PreparatorPrefix:
    data_source_params: Tuple[str, Params]
    preparator_params: Tuple[str, Params]


@dataclasses.dataclass(frozen=True)
class AlgorithmsPrefix:
    data_source_params: Tuple[str, Params]
    preparator_params: Tuple[str, Params]
    algorithm_params_list: Tuple[Tuple[str, Params], ...]


@dataclasses.dataclass(frozen=True)
class ServingPrefix:
    data_source_params: Tuple[str, Params]
    preparator_params: Tuple[str, Params]
    algorithm_params_list: Tuple[Tuple[str, Params], ...]
    serving_params: Tuple[str, Params]


class FastEvalEngineWorkflow:
    """Holds the per-sweep caches (``FastEvalEngineWorkflow``,
    ``FastEvalEngine.scala:89-344``)."""

    def __init__(self, engine: "FastEvalEngine", ctx, workflow_params: WorkflowParams):
        self.engine = engine
        self.ctx = ctx
        self.workflow_params = workflow_params
        # caches (FastEvalEngine.scala:299-302)
        self.data_source_cache: AssocCache = AssocCache()
        self.preparator_cache: AssocCache = AssocCache()
        self.algorithms_cache: AssocCache = AssocCache()
        self.serving_cache: AssocCache = AssocCache()

    # each stage: compute through the previous stage's cached result
    def get_data_source_result(self, prefix: DataSourcePrefix):
        cached = self.data_source_cache.get(prefix)
        if cached is None:
            name, params = prefix.data_source_params
            data_source = doer(self.engine.data_source_class_map[name], params)
            cached = data_source.read_eval(self.ctx)
            self.data_source_cache.put(prefix, cached)
        return cached

    def get_preparator_result(self, prefix: PreparatorPrefix):
        cached = self.preparator_cache.get(prefix)
        if cached is None:
            eval_sets = self.get_data_source_result(
                DataSourcePrefix(prefix.data_source_params)
            )
            name, params = prefix.preparator_params
            preparator = doer(self.engine.preparator_class_map[name], params)
            cached = [
                (preparator.prepare(self.ctx, td), ei, qa)
                for td, ei, qa in eval_sets
            ]
            self.preparator_cache.put(prefix, cached)
        return cached

    def get_algorithms_result(self, prefix: AlgorithmsPrefix):
        """Per fold: list over algos of indexed predictions
        (``computeAlgorithmsResult``, ``FastEvalEngine.scala:170-242``)."""
        cached = self.algorithms_cache.get(prefix)
        if cached is None:
            prepared_sets = self.get_preparator_result(
                PreparatorPrefix(
                    prefix.data_source_params, prefix.preparator_params
                )
            )
            algos = [
                doer(self.engine.algorithm_class_map[name], params)
                for name, params in prefix.algorithm_params_list
            ]
            cached = []
            for pd, ei, qa in prepared_sets:
                models = [a.train(self.ctx, pd) for a in algos]
                indexed = list(enumerate(q for q, _ in qa))
                per_algo = [
                    a.batch_predict(m, indexed)
                    for a, m in zip(algos, models)
                ]
                cached.append((per_algo, ei, qa))
            self.algorithms_cache.put(prefix, cached)
        return cached

    def get_serving_result(self, prefix: ServingPrefix):
        cached = self.serving_cache.get(prefix)
        if cached is None:
            algo_sets = self.get_algorithms_result(
                AlgorithmsPrefix(
                    prefix.data_source_params,
                    prefix.preparator_params,
                    prefix.algorithm_params_list,
                )
            )
            name, params = prefix.serving_params
            serving = doer(self.engine.serving_class_map[name], params)
            cached = []
            for per_algo, ei, qa in algo_sets:
                by_query: Dict[int, Dict[int, Any]] = defaultdict(dict)
                for ai, indexed_preds in enumerate(per_algo):
                    for qi, p in indexed_preds:
                        by_query[qi][ai] = p
                qpa = []
                for qi, (q, a) in enumerate(qa):
                    preds = by_query.get(qi, {})
                    ordered = [preds[ai] for ai in sorted(preds)]
                    qpa.append((q, serving.serve(q, ordered), a))
                cached.append((ei, qpa))
            self.serving_cache.put(prefix, cached)
        return cached


class FastEvalEngine(Engine):
    """Engine whose ``batch_eval`` memoizes by params prefix
    (``FastEvalEngine.scala:310-344``)."""

    def batch_eval(
        self,
        ctx,
        engine_params_list: Sequence[EngineParams],
        workflow_params: WorkflowParams = WorkflowParams(),
    ):
        workflow = FastEvalEngineWorkflow(self, ctx, workflow_params)
        results = []
        for ep in engine_params_list:
            prefix = ServingPrefix(
                ep.data_source_params,
                ep.preparator_params,
                tuple(ep.algorithm_params_list),
                ep.serving_params,
            )
            results.append((ep, workflow.get_serving_result(prefix)))
        return results
