"""Engine: chains DASE components, orchestrates train/eval/deploy.

Rebuild of ``core/src/main/scala/io/prediction/controller/Engine.scala``:
component class maps keyed by name, ``EngineParams`` naming one variant of
each stage, static train (``Engine.scala:499-586``) and eval
(``Engine.scala:588-672``) dataflows, deploy-time model preparation
(``prepareDeploy``, ``Engine.scala:168-237``) and engine-variant JSON parsing
(``jValueToEngineParams``, ``Engine.scala:313-370``).
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import logging
from collections import defaultdict
from typing import (
    Any,
    Dict,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
    Type,
    Union,
)

from .dase import (
    RETRAIN,
    Algorithm,
    DataSource,
    FirstServing,
    IdentityPreparator,
    PersistentModelManifest,
    Preparator,
    Serving,
    doer,
    run_sanity_check,
)
from .params import EmptyParams, Params, ParamsError, extract_params, params_to_json

logger = logging.getLogger(__name__)


def _null_phase(name: str):
    return contextlib.nullcontext()

ClassMap = Dict[str, Type]


def _as_class_map(spec: Union[Type, Mapping[str, Type]]) -> ClassMap:
    if isinstance(spec, Mapping):
        return dict(spec)
    return {"": spec}


@dataclasses.dataclass(frozen=True)
class WorkflowParams:
    """Per-run workflow knobs (``workflow/WorkflowParams.scala``; surfaced as
    CLI flags in ``CreateWorkflow.scala:87-140``)."""

    batch: str = ""
    verbose: int = 0
    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False
    #: hyperparameter-sweep parallelism: 0 = auto (one slice per candidate
    #: up to the mesh data-axis size), 1 = serial, N = N mesh slices
    eval_parallelism: int = 0
    #: per-run checkpoint cadence override (``pio train
    #: --checkpoint-every``; docs/checkpoint.md): None defers to the
    #: engine params / ``PIO_CKPT_EVERY`` tri-state
    checkpoint_every: Optional[int] = None


class StopAfterReadInterruption(Exception):
    """``--stop-after-read`` (``Engine.scala:530-536``)."""


class StopAfterPrepareInterruption(Exception):
    """``--stop-after-prepare`` (``Engine.scala:548-554``)."""


@dataclasses.dataclass(frozen=True)
class EngineParams:
    """Named (component-name, Params) bindings for one engine variant
    (``controller/EngineParams.scala:56-144``)."""

    data_source_params: Tuple[str, Params] = ("", EmptyParams())
    preparator_params: Tuple[str, Params] = ("", EmptyParams())
    algorithm_params_list: Sequence[Tuple[str, Params]] = (("", EmptyParams()),)
    serving_params: Tuple[str, Params] = ("", EmptyParams())

    def __post_init__(self):
        object.__setattr__(
            self, "algorithm_params_list", tuple(self.algorithm_params_list)
        )

    def copy(self, **updates) -> "EngineParams":
        return dataclasses.replace(self, **updates)


class Engine:
    """The DASE engine (``Engine.scala:81-128``)."""

    def __init__(
        self,
        data_source_class_map: Union[Type, Mapping[str, Type]],
        preparator_class_map: Union[Type, Mapping[str, Type]],
        algorithm_class_map: Union[Type, Mapping[str, Type]],
        serving_class_map: Union[Type, Mapping[str, Type]],
    ):
        self.data_source_class_map = _as_class_map(data_source_class_map)
        self.preparator_class_map = _as_class_map(preparator_class_map)
        self.algorithm_class_map = _as_class_map(algorithm_class_map)
        self.serving_class_map = _as_class_map(serving_class_map)

    # -- component instantiation (Engine.scala:136-145) -------------------
    def _data_source(self, ep: EngineParams) -> DataSource:
        name, params = ep.data_source_params
        if name not in self.data_source_class_map:
            raise KeyError(f"Unknown datasource name {name!r}")
        return doer(self.data_source_class_map[name], params)

    def _preparator(self, ep: EngineParams) -> Preparator:
        name, params = ep.preparator_params
        if name not in self.preparator_class_map:
            raise KeyError(f"Unknown preparator name {name!r}")
        return doer(self.preparator_class_map[name], params)

    def _algorithms(self, ep: EngineParams) -> List[Algorithm]:
        algos = []
        for name, params in ep.algorithm_params_list:
            if name not in self.algorithm_class_map:
                raise KeyError(f"Unknown algorithm name {name!r}")
            algos.append(doer(self.algorithm_class_map[name], params))
        return algos

    def _serving(self, ep: EngineParams) -> Serving:
        name, params = ep.serving_params
        if name not in self.serving_class_map:
            raise KeyError(f"Unknown serving name {name!r}")
        return doer(self.serving_class_map[name], params)

    # -- train (Engine.train instance :130-166 + static :499-586) ---------
    def train(
        self,
        ctx,
        engine_params: EngineParams,
        workflow_params: WorkflowParams = WorkflowParams(),
    ) -> List[Any]:
        """Run read → sanity → prepare → sanity → train(each algo) → sanity;
        returns one trained model per algorithm."""
        data_source = self._data_source(engine_params)
        preparator = self._preparator(engine_params)
        algorithms = self._algorithms(engine_params)
        timer = getattr(ctx, "timer", None)
        timed = timer.time if timer is not None else _null_phase

        try:
            with timed("read"):
                training_data = data_source.read_training(ctx)
        except Exception as exc:
            # Engine.scala:517-524 wraps read errors with a storage hint.
            raise RuntimeError(
                "Data is incomplete or data source reported an error. "
                f"(reading training data failed: {exc})"
            ) from exc
        if not workflow_params.skip_sanity_check:
            run_sanity_check(training_data, "training data")
        if workflow_params.stop_after_read:
            raise StopAfterReadInterruption()

        with timed("prepare"):
            prepared_data = preparator.prepare(ctx, training_data)
        if not workflow_params.skip_sanity_check:
            run_sanity_check(prepared_data, "prepared data")
        if workflow_params.stop_after_prepare:
            raise StopAfterPrepareInterruption()

        models = []
        for i, algo in enumerate(algorithms):
            if ctx is not None:
                # lets algorithms namespace per-run resources (checkpoints)
                ctx.algorithm_index = i
            with timed(f"train[{i}]"):
                model = algo.train(ctx, prepared_data)
            if not workflow_params.skip_sanity_check:
                run_sanity_check(model, "model")
            models.append(model)
        return models

    # -- persistence (Engine.makeSerializableModels :254-272) -------------
    def make_serializable_models(
        self, ctx, engine_params: EngineParams, instance_id: str, models: Sequence[Any]
    ) -> List[Any]:
        """Per algorithm: PersistentModelManifest | RETRAIN | blobbable model."""
        algorithms = self._algorithms(engine_params)
        return [
            algo.make_persistent(instance_id, model, ctx)
            for algo, model in zip(algorithms, models)
        ]

    # -- deploy (Engine.prepareDeploy :168-237) ----------------------------
    def prepare_deploy(
        self,
        ctx,
        engine_params: EngineParams,
        instance_id: str,
        persisted_models: Sequence[Any],
    ) -> List[Any]:
        """Turn persisted models back into live ones: load self-persisted
        models, retrain RETRAIN entries (``Engine.scala:180-198``), pass
        blobbed models through."""
        algorithms = self._algorithms(engine_params)
        needs_retrain = any(m is RETRAIN for m in persisted_models)
        retrained: Optional[List[Any]] = None
        if needs_retrain:
            logger.info(
                "Some persisted models require retraining at deploy "
                "(reference behavior for non-persistable models)"
            )
            retrained = self.train(ctx, engine_params)
        live = []
        for i, (algo, pm) in enumerate(zip(algorithms, persisted_models)):
            if isinstance(pm, PersistentModelManifest):
                cls = pm.resolve()
                live.append(cls.load(instance_id, algo.params, ctx))
            elif pm is RETRAIN:
                assert retrained is not None
                live.append(retrained[i])
            else:
                live.append(pm)
        return live

    # -- eval (Engine.eval static :588-672) --------------------------------
    def eval(
        self,
        ctx,
        engine_params: EngineParams,
        workflow_params: WorkflowParams = WorkflowParams(),
    ) -> List[Tuple[Any, List[Tuple[Any, Any, Any]]]]:
        """Per eval fold: train on the split, batch-predict all algorithms,
        combine per query through serving → (eval info, [(q, p, a)])."""
        data_source = self._data_source(engine_params)
        preparator = self._preparator(engine_params)
        algorithms = self._algorithms(engine_params)
        serving = self._serving(engine_params)

        eval_sets = data_source.read_eval(ctx)
        results = []
        for training_data, eval_info, qa_pairs in eval_sets:
            prepared_data = preparator.prepare(ctx, training_data)
            models = [algo.train(ctx, prepared_data) for algo in algorithms]

            # Note: serving.supplement is a serve-time hook (query server
            # path) and is intentionally not applied during evaluation,
            # matching the reference's eval dataflow and keeping
            # FastEvalEngine's prediction caches equivalent to this path.
            indexed = list(enumerate(q for q, _ in qa_pairs))
            # Union of per-algo batch predictions grouped by query index
            # (Engine.scala:636-660).
            by_query: Dict[int, Dict[int, Any]] = defaultdict(dict)
            for ai, (algo, model) in enumerate(zip(algorithms, models)):
                for qi, p in algo.batch_predict(model, indexed):
                    by_query[qi][ai] = p
            qpa = []
            for qi, (q, a) in enumerate(qa_pairs):
                preds = by_query.get(qi, {})
                ordered = [preds[ai] for ai in sorted(preds)]
                p = serving.serve(q, ordered)
                qpa.append((q, p, a))
            results.append((eval_info, qpa))
        return results

    def batch_eval(
        self,
        ctx,
        engine_params_list: Sequence[EngineParams],
        workflow_params: WorkflowParams = WorkflowParams(),
        parallelism: int = 1,
    ) -> List[Tuple[EngineParams, List[Tuple[Any, List[Tuple[Any, Any, Any]]]]]]:
        """Evaluate every EngineParams (``BaseEngine.batchEval``,
        ``core/BaseEngine.scala:47-55``); FastEvalEngine overrides with
        prefix memoization.

        ``parallelism > 1`` runs candidates concurrently on independent
        mesh slices (``WorkflowContext.slices``; SURVEY §2.8 row 5 — the
        TPU-native form of the reference's ``.par`` sweep): each
        candidate's training dispatches onto a disjoint device subset, so
        an 8-device mesh evaluates a 4-way grid as 4 concurrent 2-device
        trainings."""
        if parallelism > 1 and len(engine_params_list) > 1:
            from ..parallel.sweep import run_sliced

            tasks = [
                (lambda sliced, ep=ep: self.eval(sliced, ep, workflow_params))
                for ep in engine_params_list
            ]
            results = run_sliced(ctx, tasks, parallelism)
            return list(zip(engine_params_list, results))
        return [
            (ep, self.eval(ctx, ep, workflow_params))
            for ep in engine_params_list
        ]

    # -- engine.json parsing (Engine.scala:313-370) ------------------------
    def json_to_engine_params(self, variant: Mapping[str, Any]) -> EngineParams:
        """Parse an engine-variant JSON object into typed EngineParams."""
        ds = _named_params(variant, "datasource", self.data_source_class_map)
        prep = _named_params(variant, "preparator", self.preparator_class_map)
        serv = _named_params(variant, "serving", self.serving_class_map)

        algorithms = variant.get("algorithms")
        if algorithms is None:
            algo_list: List[Tuple[str, Params]] = [
                ("", _default_params(self.algorithm_class_map, ""))
            ]
        else:
            algo_list = []
            for block in algorithms:
                name = block.get("name", "")
                if name not in self.algorithm_class_map:
                    raise ParamsError(
                        f"Unable to find algorithm class with name {name!r} "
                        "defined in Engine."
                    )
                cls = self.algorithm_class_map[name]
                params_cls = _component_params_class(cls)
                algo_list.append(
                    (name, extract_params(params_cls, block.get("params")))
                )
        return EngineParams(
            data_source_params=ds,
            preparator_params=prep,
            algorithm_params_list=algo_list,
            serving_params=serv,
        )

    def engine_instance_to_engine_params(self, instance) -> EngineParams:
        """Rebuild EngineParams from a stored EngineInstance row
        (``Engine.scala:372-425``) — the deploy path's parameter source."""
        def parse(text: str, class_map: ClassMap, stage: str) -> Tuple[str, Params]:
            if not text:
                return ("", _default_params(class_map, ""))
            obj = json.loads(text)
            name = obj.get("name", "")
            if name not in class_map:
                raise ParamsError(
                    f"Unable to find {stage} class with name {name!r} defined "
                    "in Engine (stored engine instance refers to a renamed or "
                    "removed component)."
                )
            cls = class_map[name]
            return (name, extract_params(_component_params_class(cls), obj.get("params")))

        algo_list: List[Tuple[str, Params]] = []
        if instance.algorithms_params:
            for block in json.loads(instance.algorithms_params):
                name = block.get("name", "")
                if name not in self.algorithm_class_map:
                    raise ParamsError(
                        f"Unable to find algorithm class with name {name!r} "
                        "defined in Engine (stored engine instance refers to "
                        "a renamed or removed component)."
                    )
                cls = self.algorithm_class_map[name]
                algo_list.append(
                    (name, extract_params(_component_params_class(cls), block.get("params")))
                )
        else:
            algo_list = [("", _default_params(self.algorithm_class_map, ""))]
        return EngineParams(
            data_source_params=parse(
                instance.data_source_params, self.data_source_class_map, "datasource"
            ),
            preparator_params=parse(
                instance.preparator_params, self.preparator_class_map, "preparator"
            ),
            algorithm_params_list=algo_list,
            serving_params=parse(
                instance.serving_params, self.serving_class_map, "serving"
            ),
        )


def serialize_engine_params(ep: EngineParams) -> Dict[str, str]:
    """EngineParams → the four JSON-text columns of an EngineInstance row
    (``CreateWorkflow.scala:245-253``)."""
    def enc(pair: Tuple[str, Params]) -> str:
        return json.dumps({"name": pair[0], "params": params_to_json(pair[1])})

    return {
        "data_source_params": enc(ep.data_source_params),
        "preparator_params": enc(ep.preparator_params),
        "algorithms_params": json.dumps(
            [
                {"name": name, "params": params_to_json(params)}
                for name, params in ep.algorithm_params_list
            ]
        ),
        "serving_params": enc(ep.serving_params),
    }


def _component_params_class(component_cls: Type) -> Type:
    """Find a component's Params dataclass.

    Replacement for ctor-signature reflection: the component declares
    ``params_class`` or defaults to EmptyParams.
    """
    return getattr(component_cls, "params_class", EmptyParams)


def _named_params(
    variant: Mapping[str, Any], field: str, class_map: ClassMap
) -> Tuple[str, Params]:
    """``WorkflowUtils.getParamsFromJsonByFieldAndClass``
    (``WorkflowUtils.scala:169-209``)."""
    block = variant.get(field)
    if block is None:
        return ("", _default_params(class_map, ""))
    name = block.get("name", "")
    if name not in class_map:
        raise ParamsError(
            f"Unable to find {field} class with name {name!r} defined in Engine."
        )
    params_json = block.get("params")
    if params_json is None:
        return (name, _default_params(class_map, name))
    cls = class_map[name]
    return (name, extract_params(_component_params_class(cls), params_json))


def _default_params(class_map: ClassMap, name: str) -> Params:
    """An absent params block means "the component's declared defaults", not
    EmptyParams — otherwise a component whose ``params_class`` has required
    behavior (e.g. SeqPreparator's seq_len) breaks when the variant omits
    the block."""
    cls = class_map.get(name)
    if cls is None:
        return EmptyParams()
    params_cls = _component_params_class(cls)
    try:
        return params_cls()
    except TypeError:  # params class with required fields: caller must supply
        return EmptyParams()


class SimpleEngine(Engine):
    """Single DataSource + identity preparator + single algorithm + first
    serving (``Engine.scala:677-696``)."""

    def __init__(self, data_source_class: Type, algorithm_class: Type):
        super().__init__(
            data_source_class,
            IdentityPreparator,
            algorithm_class,
            FirstServing,
        )
