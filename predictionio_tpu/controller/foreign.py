"""Second-language engine AUTHORING: DASE components as subprocesses.

The reference ships a Java shim (~900 LoC: ``controller/java/
LJavaAlgorithm.scala``, ``LJavaDataSource.scala``, ``LJavaPreparator.scala``,
``LJavaServing.scala`` and the ``JavaEngineBuilder``) so engines can be
*written* in a second JVM language and still run inside the Scala workflow.
This module is the TPU-native rebuild of that capability with the JVM
assumption dropped: a component authored in ANY language runs as a child
process speaking line-delimited JSON over stdin/stdout, and plugs into the
same Engine/workflow/serving machinery as a Python component. The C++
authoring helper (``sdk/cpp/pio_engine.hpp``) plus a worked example
(``examples/cpp_engine/``) play the role of the reference's Java examples.

Wire protocol (one JSON object per line, child must answer in order):

    → {"id": 1, "method": "read_training", "params": {...}}
    ← {"id": 1, "result": <training data JSON>}
    → {"id": 2, "method": "prepare", "params": {...}, "data": <td>}
    ← {"id": 2, "result": <prepared data JSON>}
    → {"id": 3, "method": "train", "params": {...}, "data": <pd>}
    ← {"id": 3, "result": <model JSON>}
    → {"id": 4, "method": "load", "model": <model JSON>}
    ← {"id": 4, "result": true}
    → {"id": 5, "method": "predict", "query": {...}}
    ← {"id": 5, "result": <prediction JSON>}

Any response may instead carry ``{"error": "message"}`` — it surfaces as a
Python exception on the calling side (one failed predict fails only that
query; the micro-batcher's per-item failure channel applies). The child's
stderr passes through to the parent's stderr (debugging parity with the
reference, whose Java components log through the shared JVM).

Design notes, TPU-first: the foreign process is HOST-side code — data
sourcing, business rules, glue. The device path (jit/pallas) stays in
Python/XLA where the compiler lives; a foreign algorithm that wants TPU
compute composes with in-tree device ops by returning data for a Python
component to stage. This is the same division the reference draws: its
Java shim wraps local (L-prefix) components while the heavy lifting stays
in Spark (``LJavaAlgorithm.scala:1``).
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
from typing import Any, List, Optional, Sequence, Tuple

from .dase import Algorithm, DataSource, Preparator
from .params import Params

__all__ = [
    "ForeignProcessError",
    "ForeignParams",
    "ForeignAlgorithm",
    "ForeignDataSource",
    "ForeignPreparator",
    "ForeignModel",
]


class ForeignProcessError(RuntimeError):
    """Child process died or broke the protocol; carries a stderr tail."""


class ForeignParams(Params):
    """Parameters for a foreign component.

    ``cmd``: argv of the child process (e.g. ``["./popularity"]``).
    ``cwd``: working directory (default: the engine dir at run time).
    ``params``: arbitrary JSON passed to the child with every
    read/prepare/train call (the component's own hyperparameters).
    ``timeout_s``: per-request timeout (train may take long; size it).
    """

    def __init__(self, cmd: Sequence[str], cwd: Optional[str] = None,
                 params: Optional[dict] = None, timeout_s: float = 600.0):
        self.cmd = list(cmd)
        self.cwd = cwd
        self.params = dict(params or {})
        self.timeout_s = float(timeout_s)


class _ForeignProcess:
    """One child process + request/response plumbing (thread-safe: the
    stdio pipe is a serial channel, so concurrent predict() calls from the
    micro-batcher's pipelined workers serialize on a lock)."""

    def __init__(self, cmd: List[str], cwd: Optional[str],
                 timeout_s: float):
        self._cmd = cmd
        self._cwd = cwd
        self._timeout_s = timeout_s
        self._proc: Optional[subprocess.Popen] = None
        self._buf = bytearray()  # bytes read past the last newline
        self._lock = threading.Lock()
        self._next_id = 0

    def _ensure(self) -> subprocess.Popen:
        if self._proc is None or self._proc.poll() is not None:
            try:
                # Binary pipes: line framing, decoding, and timeouts are
                # handled here (a text-mode readline would block without
                # a deadline and raise decode errors mid-protocol).
                self._proc = subprocess.Popen(
                    self._cmd,
                    cwd=self._cwd,
                    stdin=subprocess.PIPE,
                    stdout=subprocess.PIPE,
                    # stderr passes through to the parent's stderr
                    bufsize=0,
                )
                self._buf = bytearray()
            except OSError as exc:
                raise ForeignProcessError(
                    f"cannot start foreign component {self._cmd!r}: {exc}"
                ) from exc
        return self._proc

    def request(self, method: str, timeout_s: Optional[float] = None,
                **fields) -> Any:
        """Send one request line, read one response line."""
        with self._lock:
            proc = self._ensure()
            self._next_id += 1
            req_id = self._next_id
            msg = json.dumps({"id": req_id, "method": method, **fields})
            try:
                assert proc.stdin is not None
                proc.stdin.write(msg.encode("utf-8") + b"\n")
                proc.stdin.flush()
            except (BrokenPipeError, OSError) as exc:
                raise self._died(f"write failed: {exc}")
            raw = self._read_line(
                proc,
                timeout_s if timeout_s is not None else self._timeout_s,
            )
            try:
                resp = json.loads(raw.decode("utf-8"))
            except ValueError:
                raise self._died(f"non-JSON response line: {raw[:200]!r}")
            if resp.get("id") != req_id:
                raise self._died(
                    f"response id {resp.get('id')!r} != request id {req_id}"
                )
            if "error" in resp:
                # component-level failure: the child is still healthy, so
                # this is an ordinary exception, not a process error
                raise RuntimeError(
                    f"foreign component {method} failed: {resp['error']}"
                )
            return resp.get("result")

    def _read_line(self, proc: subprocess.Popen, timeout_s: float) -> bytes:
        """Read one newline-terminated line with a WHOLE-LINE deadline —
        a child that writes a partial line and wedges must still trip the
        timeout, not block forever on the tail."""
        import select
        import time

        assert proc.stdout is not None
        fd = proc.stdout.fileno()
        deadline = time.monotonic() + timeout_s
        while True:
            nl = self._buf.find(b"\n")
            if nl >= 0:
                line = bytes(self._buf[:nl])
                del self._buf[: nl + 1]
                return line
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self.close(kill=True)
                raise ForeignProcessError(
                    f"foreign component timed out after {timeout_s}s "
                    f"({self._cmd!r})"
                )
            ready, _, _ = select.select([fd], [], [], remaining)
            if not ready:
                continue  # loop re-checks the deadline
            chunk = os.read(fd, 1 << 16)
            if not chunk:
                raise self._died("child closed stdout")
            self._buf.extend(chunk)

    def _died(self, detail: str) -> ForeignProcessError:
        rc = self._proc.poll() if self._proc else None
        self.close(kill=True)
        return ForeignProcessError(
            f"foreign component {self._cmd!r} protocol failure "
            f"(exit code {rc}): {detail}"
        )

    def close(self, kill: bool = False) -> None:
        proc, self._proc = self._proc, None
        if proc is None:
            return
        try:
            if proc.stdin:
                proc.stdin.close()
            if kill:
                proc.kill()
            proc.wait(timeout=5.0)
        except Exception:
            try:
                proc.kill()
            except Exception:
                pass

    def __del__(self):
        self.close(kill=True)


def _resolve_cwd(p: ForeignParams) -> Optional[str]:
    if p.cwd:
        return p.cwd
    # engine dir convention: run_workflow/run_server chdir is not
    # guaranteed, so a relative cmd resolves against cwd at spawn
    return None


class ForeignDataSource(DataSource):
    """DataSource authored in another language (``read_training``)."""

    def __init__(self, params: ForeignParams):
        self.params = params
        self._proc = _ForeignProcess(
            params.cmd, _resolve_cwd(params), params.timeout_s
        )

    def read_training(self, ctx) -> Any:
        return self._proc.request("read_training", params=self.params.params)


class ForeignPreparator(Preparator):
    """Preparator authored in another language (``prepare``)."""

    def __init__(self, params: ForeignParams):
        self.params = params
        self._proc = _ForeignProcess(
            params.cmd, _resolve_cwd(params), params.timeout_s
        )

    def prepare(self, ctx, training_data: Any) -> Any:
        return self._proc.request(
            "prepare", params=self.params.params, data=training_data
        )


class ForeignModel:
    """A foreign-trained model: the child's model JSON plus how to respawn
    the child at deploy time. Pickles through the standard model store
    (the workflow's default persistence path)."""

    def __init__(self, model_json: Any, cmd: List[str],
                 cwd: Optional[str], timeout_s: float):
        self.model_json = model_json
        self.cmd = cmd
        self.cwd = cwd
        self.timeout_s = timeout_s


class ForeignAlgorithm(Algorithm):
    """Algorithm authored in another language (train + predict).

    One child process per algorithm instance; after ``train`` (or after
    model load at deploy) the child holds the model in memory and serves
    ``predict`` requests over the pipe. Under the serving micro-batcher
    the pipe serializes concurrent predicts — a foreign algorithm is a
    host-side component and is not expected to hit device-path QPS."""

    def __init__(self, params: ForeignParams):
        self.params = params
        self._proc = _ForeignProcess(
            params.cmd, _resolve_cwd(params), params.timeout_s
        )
        # Strong reference to the model currently loaded in the child:
        # identity via `is` (an id() cache would go stale when CPython
        # recycles a freed object's address).
        self._loaded_model: Optional[ForeignModel] = None

    def train(self, ctx, prepared_data: Any) -> ForeignModel:
        model_json = self._proc.request(
            "train", params=self.params.params, data=prepared_data
        )
        model = ForeignModel(
            model_json, self.params.cmd, self.params.cwd,
            self.params.timeout_s,
        )
        self._loaded_model = model  # train leaves the model loaded
        return model

    def _ensure_loaded(self, model: ForeignModel) -> None:
        if self._loaded_model is model:
            # fast path — but the child may have died since
            proc = self._proc._proc
            if proc is not None and proc.poll() is None:
                return
        self._proc.request("load", model=model.model_json)
        self._loaded_model = model

    def predict(self, model: ForeignModel, query: Any) -> Any:
        if not isinstance(model, ForeignModel):
            raise TypeError(
                f"ForeignAlgorithm got a {type(model).__name__} model; "
                "expected ForeignModel"
            )
        self._ensure_loaded(model)
        q = query if isinstance(query, dict) else getattr(
            query, "__dict__", query
        )
        return self._proc.request("predict", query=q)
