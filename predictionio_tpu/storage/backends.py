"""Pluggable storage-backend families.

Rebuild of the reference's reflective DAO lookup
(``data/src/main/scala/io/prediction/data/storage/Storage.scala:176-217``):
there, a source ``type`` string like ``elasticsearch`` resolves to classes
``io.prediction.data.storage.elasticsearch.ESApps`` etc. by classname
reflection, so a new backend drops in without editing ``Storage.scala``.

The Python analogue is a registration table plus import-time discovery:

* A backend family calls :func:`register_backend` (usually at module import)
  with factories for whichever repositories it supports.
* When the registry meets an unknown ``type``, it tries, in order:
  the source's ``module`` conf key (``PIO_STORAGE_SOURCES_<NAME>_MODULE`` —
  the escape hatch for third-party packages), then
  ``predictionio_tpu.storage.<type>`` — importing either is expected to
  register the family as a side effect, exactly like JVM classloading in the
  reference.

Each factory receives the full source conf dict (the lower-cased
``PIO_STORAGE_SOURCES_<NAME>_*`` key/values, e.g. ``path``, ``host``,
``port``) so families define their own connection surface, mirroring how the
reference passes ``StorageClientConfig(hosts, ports)`` through to backend
constructors (``Storage.scala:124-174``).
"""

from __future__ import annotations

import dataclasses
import importlib
import threading
from typing import Callable, Dict, Optional

SourceConf = Dict[str, str]


@dataclasses.dataclass(frozen=True)
class BackendFamily:
    """One storage backend family (= one reference backend package).

    A family may serve any subset of the three repositories; ``None`` means
    "this family cannot back that repository" (parity with the reference,
    where e.g. mongodb provides metadata DAOs but no events —
    ``Storage.scala:193-204`` simply fails to find the class).
    """

    name: str
    events: Optional[Callable[[SourceConf], object]] = None
    metadata: Optional[Callable[[SourceConf], object]] = None
    models: Optional[Callable[[SourceConf], object]] = None


class BackendLookupError(Exception):
    """No family provides the requested (type, repository) pair."""


_FAMILIES: Dict[str, BackendFamily] = {}
_LOCK = threading.Lock()


def register_backend(family: BackendFamily) -> None:
    """Register (or replace) a backend family. Idempotent per name —
    re-import of a backend module must not fail."""
    with _LOCK:
        _FAMILIES[family.name] = family


def registered_backends() -> Dict[str, BackendFamily]:
    with _LOCK:
        return dict(_FAMILIES)


def resolve_backend(stype: str, conf: Optional[SourceConf] = None) -> BackendFamily:
    """Find the family for a source ``type``, importing its module on demand.

    Discovery order mirrors the reference's classname reflection
    (``Storage.scala:176-191``): explicit ``module`` conf key first (the
    third-party hook), then the in-tree package ``predictionio_tpu.storage.
    <type>``.
    """
    with _LOCK:
        fam = _FAMILIES.get(stype)
    if fam is not None:
        return fam

    candidates = []
    if conf and conf.get("module"):
        candidates.append(conf["module"])
    candidates.append(f"predictionio_tpu.storage.{stype}")

    errors = []
    for mod in candidates:
        try:
            importlib.import_module(mod)
        except ImportError as exc:
            errors.append(f"{mod}: {exc}")
            continue
        with _LOCK:
            fam = _FAMILIES.get(stype)
        if fam is not None:
            return fam
        errors.append(f"{mod}: imported but did not register type {stype!r}")

    raise BackendLookupError(
        f"No storage backend family for type {stype!r} "
        f"(registered: {sorted(registered_backends())}; tried modules: "
        f"{'; '.join(errors)})"
    )


def make_store(stype: str, repo_kind: str, conf: SourceConf) -> object:
    """Construct a store for one repository kind ('events' | 'metadata' |
    'models') — the ``Storage.getDataObject`` analogue."""
    fam = resolve_backend(stype, conf)
    factory = getattr(fam, repo_kind, None)
    if factory is None:
        raise BackendLookupError(
            f"Backend family {stype!r} does not support the {repo_kind} "
            "repository"
        )
    return factory(conf)
