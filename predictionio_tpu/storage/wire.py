"""JSON wire codec for metadata records.

The remote storage backend (``storage/remote.py`` ↔
``storage/storage_server.py``) ships MetadataStore arguments and results as
JSON. The reference does the same job with Elasticsearch document
serializers (one json4s codec per DAO, e.g.
``data/src/main/scala/io/prediction/data/storage/elasticsearch/ESEngineInstances.scala``);
here one generic dataclass codec covers all record types: a tagged envelope
``{"__dc__": "EngineInstance", ...fields}`` for dataclasses and
``{"__dt__": iso8601}`` for datetimes, everything else plain JSON.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
from typing import Any, Dict, Type

from .metadata import (
    AccessKey,
    App,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
    RolloutPlan,
)

_RECORD_TYPES: Dict[str, Type] = {
    cls.__name__: cls
    for cls in (
        App,
        AccessKey,
        EngineManifest,
        EngineInstance,
        EvaluationInstance,
        RolloutPlan,
    )
}


def encode(obj: Any) -> Any:
    """Python value → JSON-safe value."""
    if dataclasses.is_dataclass(obj) and type(obj).__name__ in _RECORD_TYPES:
        out = {"__dc__": type(obj).__name__}
        for f in dataclasses.fields(obj):
            out[f.name] = encode(getattr(obj, f.name))
        return out
    if isinstance(obj, _dt.datetime):
        return {"__dt__": obj.isoformat()}
    if isinstance(obj, dict):
        return {k: encode(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [encode(v) for v in obj]
    return obj


def decode(obj: Any) -> Any:
    """JSON value → Python value (inverse of :func:`encode`)."""
    if isinstance(obj, dict):
        if "__dt__" in obj and len(obj) == 1:
            return _dt.datetime.fromisoformat(obj["__dt__"])
        if "__dc__" in obj:
            cls = _RECORD_TYPES[obj["__dc__"]]
            fields = {
                k: decode(v) for k, v in obj.items() if k != "__dc__"
            }
            # Sequence fields (AccessKey.events, EngineManifest.files) come
            # back as lists; the dataclasses accept any Sequence.
            return cls(**fields)
        return {k: decode(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [decode(v) for v in obj]
    return obj
