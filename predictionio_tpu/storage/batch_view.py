"""Batch views over an app's events (DEPRECATED — parity shim).

Rebuild of the reference's deprecated batch-view layer
(``data/src/main/scala/io/prediction/data/view/LBatchView.scala:1-195``):
an eagerly-materialized event list with filter combinators, per-entity
time-ordered folds, and ``aggregateProperties``. The reference marked the
whole package ``/* Deprecated */`` and superseded it with
``LEvents.aggregateProperties`` — whose analogue here is
:meth:`EventStore.aggregate_properties`, the API new code should use.
This module exists for coverage of code that was written against the
view API; constructing a view emits a :class:`DeprecationWarning`.

Semantics preserved from the reference:

- ``filter(event=..., entity_type=..., start_time=..., until_time=...)``
  composes predicates over the materialized list (``EventSeq.filter``,
  ``LBatchView.scala:104-118``). NOTE the reference's start-time
  predicate is EXCLUSIVE (``!(before || equal)``) while its until-time
  is also exclusive — both faithfully mirrored, even though the storage
  layer's own ``EventFilter`` uses the conventional inclusive start.
- ``aggregate_by_entity_ordered(init, op)`` groups by entityId and folds
  each group ordered by event time (``LBatchView.scala:119-126``).
- ``aggregate_properties(entity_type)`` folds ``$set``/``$unset``/
  ``$delete`` in event order via the same DataMap rules as
  ``ViewAggregators.getDataMapAggregator`` (``LBatchView.scala:67-91``):
  unlike the modern monoid (``storage/aggregator.py``), this LEGACY fold
  applies ops strictly in event order with no timestamp tie-breaking —
  that is the deprecated layer's documented behavior, kept verbatim.
"""

from __future__ import annotations

import datetime as _dt
import warnings
from typing import Any, Callable, Dict, List, Optional

from .data_map import DataMap
from .event import SPECIAL_EVENTS, Event
from .events import EventFilter, EventStore

__all__ = ["EventSeq", "BatchView"]


class EventSeq:
    """Filterable materialized event list (``EventSeq``,
    ``LBatchView.scala:103-128``)."""

    def __init__(self, events: List[Event]):
        self.events = list(events)

    def __iter__(self):
        return iter(self.events)

    def __len__(self) -> int:
        return len(self.events)

    def filter(
        self,
        event: Optional[str] = None,
        entity_type: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        predicate: Optional[Callable[[Event], bool]] = None,
    ) -> "EventSeq":
        def utc(t: Optional[_dt.datetime]) -> Optional[_dt.datetime]:
            # same convention as EventFilter: naive bounds are taken as
            # UTC (event times are always tz-aware)
            if t is not None and t.tzinfo is None:
                return t.replace(tzinfo=_dt.timezone.utc)
            return t

        start_time, until_time = utc(start_time), utc(until_time)
        out = self.events
        if event is not None:
            out = [e for e in out if e.event == event]
        if start_time is not None:
            # reference quirk: start is EXCLUSIVE here
            # (ViewPredicates.getStartTimePredicate)
            out = [e for e in out if e.event_time > start_time]
        if until_time is not None:
            out = [e for e in out if e.event_time < until_time]
        if entity_type is not None:
            out = [e for e in out if e.entity_type == entity_type]
        if predicate is not None:
            out = [e for e in out if predicate(e)]
        return EventSeq(out)

    def aggregate_by_entity_ordered(
        self, init: Any, op: Callable[[Any, Event], Any]
    ) -> Dict[str, Any]:
        """Group by entityId, fold each group ordered by event time
        (``aggregateByEntityOrdered``, ``LBatchView.scala:119-126``)."""
        groups: Dict[str, List[Event]] = {}
        for e in self.events:
            groups.setdefault(e.entity_id, []).append(e)
        out: Dict[str, Any] = {}
        for entity_id, evs in groups.items():
            acc = init
            for e in sorted(evs, key=lambda e: e.event_time):
                acc = op(acc, e)
            out[entity_id] = acc
        return out


def _data_map_aggregator(
    acc: Optional[DataMap], e: Event
) -> Optional[DataMap]:
    """``ViewAggregators.getDataMapAggregator`` (``LBatchView.scala:67-91``):
    strictly event-ordered $set/$unset/$delete fold."""
    if e.event == "$set":
        if acc is None:
            return e.properties
        return acc.merge(e.properties)  # the reference's ``++``
    if e.event == "$unset":
        if acc is None:
            return None
        return acc.without(e.properties.keyset())  # the reference's ``--``
    if e.event == "$delete":
        return None
    return acc  # do nothing for others


class BatchView:
    """``LBatchView(appId, startTime, untilTime)``: eagerly reads the
    window's events once; every aggregate derives from that snapshot."""

    def __init__(
        self,
        store: EventStore,
        app_id: int,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
    ):
        warnings.warn(
            "BatchView is deprecated (parity with the reference's "
            "deprecated data.view package); use "
            "EventStore.aggregate_properties / find instead",
            DeprecationWarning,
            stacklevel=2,
        )
        self._store = store
        self.app_id = app_id
        self.start_time = start_time
        self.until_time = until_time
        # eager materialization, like the reference's lazy-val-forced list
        self.events = EventSeq(
            list(
                store.find(
                    app_id,
                    EventFilter(
                        start_time=start_time, until_time=until_time
                    ),
                )
            )
        )

    def aggregate_properties(
        self,
        entity_type: str,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
    ) -> Dict[str, DataMap]:
        """``LBatchView.aggregateProperties`` (``LBatchView.scala:143-166``):
        entity → folded DataMap, entities resolving to None dropped."""
        folded = (
            self.events.filter(
                entity_type=entity_type,
                start_time=start_time,
                until_time=until_time,
            )
            .filter(predicate=lambda e: e.event in SPECIAL_EVENTS)
            .aggregate_by_entity_ordered(None, _data_map_aggregator)
        )
        return {k: v for k, v in folded.items() if v is not None}
