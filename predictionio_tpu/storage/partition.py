"""Partitioned write path: the event store's keyspace → partition math.

The reference system leaned on HBase's region partitioning to scale
ingest (PAPER.md L1); this module is the rebuild's equivalent contract
(``docs/storage.md#partitioning``): the event keyspace is split across
``N`` write primaries by a **pure hash of (app, entity)** — the same
SHA-256 bucket primitive everything sticky already rides
(:func:`~predictionio_tpu.rollout.plan.bucket_for_key`), under a salt
deliberately distinct from both the rollout plan salts (minted per
plan) and the router's replica-affinity salt, so repartitioning the
store can never reshuffle canary splits or backend affinity (and vice
versa).

Everything here is a deterministic function of its string inputs — no
process state, no randomness — so every writer (event server, SDK
client, chaos drill) and every reader (feed watcher, failover probe)
computes the *same* owner for a key with zero coordination. The
golden-vector test in ``tests/test_partition.py`` pins exact outputs:
changing this mapping silently would strand every already-stored
event on the wrong primary.

Partitioned endpoint syntax (``docs/storage.md#partitioning``)::

    pio+ha://p0:7079,p0r:7079;p1:7079,p1r:7079

``;`` separates partitions (index = position), ``,`` separates the
endpoints *within* one partition (primary first, warm standbys after —
exactly the single-chain ``pio+ha://`` syntax, N times). A URL with no
``;`` is the 1-partition degenerate case, so every existing single
primary config is already a valid partitioned config.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

__all__ = [
    "PARTITION_SALT",
    "partition_for_event",
    "partition_for_key",
    "partition_key",
    "split_partition_sets",
]

#: the keyspace salt. NOT a rollout salt (minted per plan) and NOT the
#: router's ``routing_salt`` — one hash primitive, three independent
#: assignments (docs/fleet.md's one-hash design, applied to storage).
PARTITION_SALT = "pio-event-partition-v1"

_bucket_for_key = None  # resolved lazily: rollout imports storage


def _bucket(key: str) -> int:
    # Lazy import: ``rollout.plan`` is pure/stdlib, but importing it at
    # module level would run ``rollout/__init__`` → manager → storage
    # mid-initialization. At first call every package is complete.
    global _bucket_for_key
    if _bucket_for_key is None:
        from ..rollout.plan import bucket_for_key

        _bucket_for_key = bucket_for_key
    return _bucket_for_key(PARTITION_SALT, key)


def partition_key(app_id: int, entity_id: str) -> str:
    """The string the partition hash runs over: app + entity, so one
    entity's events always land on one primary (its oplog is a total
    order for that entity) while apps spread across the fleet."""
    return f"{int(app_id)}|{entity_id}"


def partition_for_key(count: int, key: str) -> int:
    """Owning partition index for ``key`` among ``count`` partitions.
    ``count == 1`` short-circuits to 0 — the unpartitioned fast path
    never pays a hash."""
    if count <= 1:
        return 0
    return _bucket(key) % count


def partition_for_event(count: int, app_id: int, entity_id: str) -> int:
    return partition_for_key(count, partition_key(app_id, entity_id))


def split_partition_sets(base_url: str) -> List[str]:
    """A (possibly partitioned) storage URL → one single-chain URL per
    partition, index = position. ``pio+ha://a;b,c`` →
    ``["pio+ha://a", "pio+ha://b,c"]``; a URL without ``;`` (including
    plain ``http://`` endpoints) is one partition."""
    base_url = base_url.strip()
    if ";" not in base_url:
        return [base_url]
    prefix = ""
    body = base_url
    if base_url.startswith("pio+ha://"):
        prefix = "pio+ha://"
        body = base_url[len(prefix):]
    parts = [p.strip().strip(",") for p in body.split(";")]
    parts = [p for p in parts if p]
    if not parts:
        raise ValueError(f"no partitions in storage URL {base_url!r}")
    return [prefix + p if prefix else p for p in parts]


def partition_primaries(base_url: str) -> List[str]:
    """The write primary (first endpoint) of every partition — what the
    continuous plane tails, one changefeed per entry."""
    out: List[str] = []
    for part in split_partition_sets(base_url):
        if part.startswith("pio+ha://"):
            first = part[len("pio+ha://"):].split(",")[0].strip()
            out.append(first if "://" in first else f"http://{first}")
        else:
            out.append(part.rstrip("/"))
    return out


def check_partition(
    declared: Optional[Sequence[int]], index: int, count: int
) -> None:
    """Loud mismatch guard shared by the oplog meta and the replica
    tailer: a node configured as partition ``index``/``count`` must
    never adopt a log minted for a different slot — silently tailing or
    extending the wrong partition's history diverges the keyspace."""
    if declared is None:
        return
    want = [int(index), int(count)]
    if [int(v) for v in declared] != want:
        raise ValueError(
            f"partition mismatch: log belongs to partition "
            f"{list(declared)}, this node is configured as {want}"
        )
