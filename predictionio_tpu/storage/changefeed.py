"""Changefeed: sequence-numbered mutation recording + idempotent replay.

The primary storage server routes every mutating op through a
:class:`Changefeed`: the op is applied to the backing store and appended
to the durable :class:`~predictionio_tpu.storage.oplog.OpLog` under one
lock, so the log is a **total order** of the store's mutations (the
WAL-shipping discipline of the reference's HBase regionservers —
replication is log replay, ``docs/storage.md#replication``). The
assigned sequence number rides back to the client in the ``X-PIO-Seq``
response header, becoming the read-your-writes token the HA client
(``storage/remote.py``) forwards to replicas as ``X-PIO-Min-Seq``.

Logged ops are **resolved**: every event carries its final ``eventId``
(minted ids are random, so replay must ship them, not re-mint), metadata
inserts carry their assigned record ids, and ``gen_next`` ships the
*value* it produced (replayed as an idempotent advance-to-at-least).
That makes :func:`apply_op` safe to re-run over any suffix of the log —
a replica that crashed between applying a batch and persisting its
progress marker simply re-applies; every op converges (upsert/delete/
advance semantics), which is the "idempotent replay keyed on seq"
contract replicas rely on.

Ordering caveat (documented, deliberate): the store apply happens
*before* the log append, inside the lock. A primary crash in between
leaves the op applied locally but absent from the feed — the client was
never acked (no seq header went out), so no acked read is lost; the
primary and a later-promoted replica may disagree about that single
unacked op, exactly like any async-replicated system.
"""

from __future__ import annotations

import base64
import dataclasses
import threading
from typing import List, Optional, Sequence, Tuple

from .event import Event
from .model_store import Model
from .oplog import OpLog
from .sqlite_events import make_event_id
from .wire import decode, encode

#: response header carrying the seq assigned to a mutating op
SEQ_HEADER = "X-PIO-Seq"
#: request header: the minimum applied seq a replica read requires
MIN_SEQ_HEADER = "X-PIO-Min-Seq"


class WrongPartition(Exception):
    """An event write reached a partitioned primary that does not own
    its (app, entity) key (``docs/storage.md#partitioning``). Accepting
    it would fork the keyspace: the event's *owning* partition's oplog
    would never carry it, so replicas, the feed watcher and failover all
    disagree about history. The hash contract is enforced loudly at the
    one place every mutation already passes through."""

    def __init__(self, message: str, expected: int):
        super().__init__(message)
        #: the partition index the key actually hashes to
        self.expected = expected

#: MetadataStore methods that mutate (the complement of the read RPCs);
#: an explicit list, like METADATA_RPC_METHODS — replication of a future
#: method must be a decision, never an accident.
METADATA_MUTATING_METHODS = frozenset(
    {
        "gen_next",
        "app_insert",
        "app_update",
        "app_delete",
        "access_key_insert",
        "access_key_delete",
        "manifest_update",
        "engine_instance_insert",
        "engine_instance_update",
        "engine_instance_delete",
        "evaluation_instance_insert",
        "evaluation_instance_update",
        "rollout_plan_upsert",
    }
)

#: the subset of metadata mutations that can move a router's cache
#: epoch (``plan_epoch + latest completed instance`` — see
#: ``fleet/router.py``): rollout plan writes change the plan half,
#: engine-instance writes can change which instance is "latest
#: completed". The pushed-invalidation subscribers
#: (docs/fleet.md#shared-cache-tier) flush on exactly these; like
#: METADATA_MUTATING_METHODS above, membership of a future method is a
#: decision, never an accident.
EPOCH_MUTATING_METHODS = frozenset(
    {
        "rollout_plan_upsert",
        "engine_instance_insert",
        "engine_instance_update",
        "engine_instance_delete",
    }
)


def op_moves_epoch(op: dict) -> bool:
    """True when a changefeed op may move the serving epoch — the
    pushed-invalidation filter. Anything unrecognized answers True for
    ``kind == "meta"`` (a NEW metadata mutation defaults to "flush", the
    fail-soft direction: a spurious flush costs a re-read, a missed one
    costs staleness)."""
    if not isinstance(op, dict) or op.get("kind") != "meta":
        return False
    method = op.get("method")
    if method in METADATA_MUTATING_METHODS:
        return method in EPOCH_MUTATING_METHODS
    return True


def _resolve_events(events: Sequence[Event]) -> List[Event]:
    """Mint ids for events that lack one (same mint the stores use), so
    the logged op replays to byte-identical records."""
    return [
        e if e.event_id is not None
        else dataclasses.replace(e, event_id=make_event_id(e))
        for e in events
    ]


class Changefeed:
    """Primary-side recorder: apply-then-log under one total-order lock.

    On a partitioned primary (the oplog carries a partition slot, or an
    explicit ``partition=(index, count)`` is passed) every event write
    is checked against the hash contract first — a misrouted event
    raises :class:`WrongPartition` *before* touching store or log."""

    def __init__(self, oplog: OpLog, events, metadata, models,
                 partition: Optional[Tuple[int, int]] = None):
        self.oplog = oplog
        self._events = events
        self._metadata = metadata
        self._models = models
        if partition is None and oplog.partition is not None:
            partition = (oplog.partition[0], oplog.partition[1])
        #: ``(index, count)``; ``count == 1`` disables the ownership check
        self.partition: Tuple[int, int] = (
            (int(partition[0]), int(partition[1]))
            if partition is not None
            else (0, 1)
        )
        # One lock across apply+append: two concurrent upserts of the same
        # key must reach the log in the order they reached the store, or a
        # replica converges to the loser. Serializing mutations is the
        # price of a total order (reads never take this lock).
        self._lock = threading.Lock()

    @property
    def last_seq(self) -> int:
        return self.oplog.last_seq

    def adopt_slot(self, index: int, count: int) -> None:
        """Claim partition slot ``(index, count)`` for this feed and its
        oplog — the live-migration path where an empty pre-layout log
        joins the new layout (see :meth:`OpLog.adopt_slot` for the
        history guard)."""
        self.oplog.adopt_slot(index, count)
        self.partition = (int(index), int(count))

    def _check_owner(self, event: Event, app_id: int) -> None:
        index, count = self.partition
        if count <= 1:
            return
        from .partition import partition_for_event

        expected = partition_for_event(count, app_id, event.entity_id)
        if expected != index:
            raise WrongPartition(
                f"event for app {app_id} entity {event.entity_id!r} "
                f"belongs to partition {expected}, this primary owns "
                f"partition {index} of {count}",
                expected=expected,
            )

    # -- events -----------------------------------------------------------
    def insert_event(self, event: Event, app_id: int) -> Tuple[str, int]:
        self._check_owner(event, app_id)
        with self._lock:
            event_id = self._events.insert(event, app_id)
            d = event.to_json_dict()
            d["eventId"] = event_id
            seq = self.oplog.append(
                {"kind": "event_insert", "app": int(app_id), "event": d}
            )
            return event_id, seq

    def write_events(
        self, events: Sequence[Event], app_id: int, fresh: bool
    ) -> int:
        """Bulk write. Keeps the store's fast paths: runs of id-less
        events (fresh by construction once minted) take ``write_new``,
        caller-explicit ids take the upsert ``insert`` — the same routing
        ``NativeEventStore.write`` does internally."""
        events = list(events)
        for event in events:
            self._check_owner(event, app_id)
        resolved = _resolve_events(events)
        with self._lock:
            if fresh:
                self._events.write_new(resolved, app_id)
            else:
                run: List[Event] = []
                for orig, res in zip(events, resolved):
                    if orig.event_id is None:
                        run.append(res)
                        continue
                    if run:
                        self._events.write_new(run, app_id)
                        run = []
                    self._events.insert(orig, app_id)
                if run:
                    self._events.write_new(run, app_id)
            return self.oplog.append(
                {
                    "kind": "event_write",
                    "app": int(app_id),
                    "events": [e.to_json_dict() for e in resolved],
                }
            )

    def delete_event(self, event_id: str, app_id: int) -> Tuple[bool, Optional[int]]:
        with self._lock:
            found = self._events.delete(event_id, app_id)
            if not found:
                return False, None  # no state change, nothing to ship
            seq = self.oplog.append(
                {"kind": "event_delete", "app": int(app_id), "eventId": event_id}
            )
            return True, seq

    def init_app(self, app_id: int) -> Tuple[bool, int]:
        with self._lock:
            ok = self._events.init(app_id)
            seq = self.oplog.append({"kind": "event_init", "app": int(app_id)})
            return ok, seq

    def remove_app(self, app_id: int) -> Tuple[bool, int]:
        with self._lock:
            ok = self._events.remove(app_id)
            seq = self.oplog.append({"kind": "event_remove", "app": int(app_id)})
            return ok, seq

    # -- metadata ---------------------------------------------------------
    def metadata_rpc(self, method: str, args: list):
        """Run one (mutating) metadata RPC, logging the *resolved* op.
        Returns ``(result, seq_or_None)`` — None when the call changed
        nothing (failed insert, no-row update/delete)."""
        if method not in METADATA_MUTATING_METHODS:
            return getattr(self._metadata, method)(*args), None
        with self._lock:
            if method == "gen_next":
                value = self._metadata.gen_next(args[0])
                seq = self.oplog.append(
                    {"kind": "meta_seq", "name": args[0], "value": value}
                )
                return value, seq
            result = getattr(self._metadata, method)(*args)
            logged = self._resolve_meta_args(method, args, result)
            if logged is None:
                return result, None
            seq = self.oplog.append(
                {
                    "kind": "meta",
                    "method": method,
                    "args": [encode(a) for a in logged],
                }
            )
            return result, seq

    @staticmethod
    def _resolve_meta_args(method: str, args: list, result):
        """The args to log, with store-assigned ids substituted in; None
        when the call was a no-op (nothing to replicate)."""
        if method in ("app_insert", "access_key_insert"):
            if result is None:
                return None  # IntegrityError path: no state change
            field = "id" if method == "app_insert" else "key"
            return [dataclasses.replace(args[0], **{field: result})] + args[1:]
        if method in (
            "engine_instance_insert",
            "evaluation_instance_insert",
            "rollout_plan_upsert",
        ):
            return [dataclasses.replace(args[0], id=result)] + args[1:]
        if result is False:
            return None  # update/delete that matched no row
        return args

    # -- models -----------------------------------------------------------
    def put_model(self, model: Model) -> int:
        with self._lock:
            self._models.insert(model)
            return self.oplog.append(
                {
                    "kind": "model_put",
                    "id": model.id,
                    "data": base64.b64encode(model.models).decode("ascii"),
                }
            )

    def delete_model(self, model_id: str) -> int:
        with self._lock:
            self._models.delete(model_id)
            return self.oplog.append({"kind": "model_delete", "id": model_id})


def apply_op(op: dict, events, metadata, models) -> None:
    """Replay one logged op against local stores. Idempotent: every op
    is an upsert / delete / advance keyed on an id carried in the op, so
    re-applying any suffix of the log converges to the same state."""
    kind = op.get("kind")
    if kind == "event_insert":
        # explicit-id insert == upsert in every backend
        events.insert(Event.from_json_dict(op["event"]), op["app"])
    elif kind == "event_write":
        # every logged event carries its id → per-event upsert replay
        events.write(
            [Event.from_json_dict(d) for d in op["events"]], op["app"]
        )
    elif kind == "event_delete":
        events.delete(op["eventId"], op["app"])
    elif kind == "event_init":
        events.init(op["app"])
    elif kind == "event_remove":
        events.remove(op["app"])
    elif kind == "meta_seq":
        metadata.sequence_advance_to(op["name"], int(op["value"]))
    elif kind == "meta":
        method = op["method"]
        if method not in METADATA_MUTATING_METHODS:
            raise ValueError(f"refusing to replay non-mutating RPC {method!r}")
        getattr(metadata, method)(*[decode(a) for a in op["args"]])
    elif kind == "model_put":
        models.insert(Model(id=op["id"], models=base64.b64decode(op["data"])))
    elif kind == "model_delete":
        models.delete(op["id"])
    else:
        raise ValueError(f"unknown changefeed op kind {kind!r}")


class RecordingMetadata:
    """A MetadataStore proxy that routes every mutating RPC through a
    :class:`Changefeed`, so in-process fleets (drills, tests) get a real
    oplog under their metadata writes without running a storage server.
    Reads pass straight through. This is exactly the storage server's
    routing, packaged for embedding — the pushed-invalidation
    subscribers (docs/fleet.md#shared-cache-tier) tail the resulting
    feed."""

    def __init__(self, changefeed: Changefeed, metadata):
        self._changefeed = changefeed
        self._metadata = metadata

    def __getattr__(self, name: str):
        if name in METADATA_MUTATING_METHODS:
            def call(*args):
                result, _seq = self._changefeed.metadata_rpc(
                    name, list(args)
                )
                return result
            return call
        return getattr(self._metadata, name)


class RecordingRegistry:
    """A StorageRegistry facade whose metadata surface is a
    :class:`RecordingMetadata` — drop-in for servers that take a
    registry, used by the shared-cache drill to give routers a live
    metadata changefeed to subscribe to."""

    def __init__(self, registry, changefeed: Changefeed):
        self._registry = registry
        self._metadata = RecordingMetadata(
            changefeed, registry.get_metadata()
        )

    def get_metadata(self):
        return self._metadata

    def __getattr__(self, name: str):
        return getattr(self._registry, name)
