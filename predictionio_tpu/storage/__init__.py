"""Storage plane: events, metadata, models, ID maps.

Rebuild of the reference's L1 storage abstraction
(``data/src/main/scala/io/prediction/data/storage/``; SURVEY §1 L1, §2.2).
"""

from .aggregator import (
    AGGREGATOR_EVENT_NAMES,
    EventOp,
    aggregate_properties,
    aggregate_single,
)
from .bimap import BiMap, EntityMap, HashedIdMap
from .data_map import DataMap, DataMapException, PropertyMap
from .event import (
    Event,
    EventValidationError,
    format_event_time,
    parse_event_time,
    utcnow,
    validate_event,
)
from .events import EventFilter, EventStore
from .metadata import (
    STATUS_COMPLETED,
    STATUS_EVALCOMPLETED,
    STATUS_EVALUATING,
    STATUS_INIT,
    STATUS_TRAINING,
    AccessKey,
    App,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
    MetadataStore,
    RolloutPlan,
    new_engine_instance,
)
from .model_store import LocalFSModelStore, Model, ModelStore, SqliteModelStore
from .registry import StorageError, StorageRegistry, get_registry
from .sqlite_events import SqliteEventStore

__all__ = [
    "AGGREGATOR_EVENT_NAMES",
    "AccessKey",
    "App",
    "BiMap",
    "HashedIdMap",
    "DataMap",
    "DataMapException",
    "EngineInstance",
    "EngineManifest",
    "EntityMap",
    "EvaluationInstance",
    "Event",
    "EventFilter",
    "EventOp",
    "EventStore",
    "EventValidationError",
    "LocalFSModelStore",
    "MetadataStore",
    "Model",
    "ModelStore",
    "PropertyMap",
    "RolloutPlan",
    "STATUS_COMPLETED",
    "STATUS_EVALCOMPLETED",
    "STATUS_EVALUATING",
    "STATUS_INIT",
    "STATUS_TRAINING",
    "SqliteEventStore",
    "SqliteModelStore",
    "StorageError",
    "StorageRegistry",
    "aggregate_properties",
    "aggregate_single",
    "format_event_time",
    "get_registry",
    "new_engine_instance",
    "parse_event_time",
    "utcnow",
    "validate_event",
]
