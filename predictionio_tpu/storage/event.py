"""Event model and validation.

Rebuild of the reference's event record and validation rules
(``data/src/main/scala/io/prediction/data/storage/Event.scala:37-115``):
an append-only, immutable event with entity / optional target-entity
addressing, a schema-free property bag, and reserved-name rules for the
``$set/$unset/$delete`` special events and the ``pio_`` prefix.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import hashlib
from typing import Any, Mapping, Optional, Sequence, Union

from .data_map import DataMap

UTC = _dt.timezone.utc

#: Single-entity reserved events (``Event.scala:66``).
SPECIAL_EVENTS = frozenset({"$set", "$unset", "$delete"})

#: Entity types exempt from the reserved-prefix rule (``Event.scala:102``).
BUILTIN_ENTITY_TYPES = frozenset({"pio_pr"})

#: Property names exempt from the reserved-prefix rule (``Event.scala:103``).
BUILTIN_PROPERTIES: frozenset = frozenset()


class EventValidationError(ValueError):
    """An event violates the reference's validation rules."""


def is_reserved_prefix(name: str) -> bool:
    """``$``- or ``pio_``-prefixed names are reserved (``Event.scala:63-64``)."""
    return name.startswith("$") or name.startswith("pio_")


def is_special_event(name: str) -> bool:
    return name in SPECIAL_EVENTS


def utcnow() -> _dt.datetime:
    return _dt.datetime.now(tz=UTC)


def _as_datetime(value: Union[_dt.datetime, str, None]) -> Optional[_dt.datetime]:
    if value is None or isinstance(value, _dt.datetime):
        if isinstance(value, _dt.datetime) and value.tzinfo is None:
            # Reference default time zone is UTC (Event.scala:59).
            return value.replace(tzinfo=UTC)
        return value
    if isinstance(value, str):
        return parse_event_time(value)
    raise EventValidationError(f"Cannot interpret {value!r} as a datetime")


def parse_event_time(text: str) -> _dt.datetime:
    """Parse an ISO-8601 timestamp; naive times are taken as UTC."""
    t = text.strip()
    if t.endswith("Z") or t.endswith("z"):
        t = t[:-1] + "+00:00"
    try:
        parsed = _dt.datetime.fromisoformat(t)
    except ValueError as exc:
        raise EventValidationError(f"Invalid event time {text!r}: {exc}") from exc
    if parsed.tzinfo is None:
        parsed = parsed.replace(tzinfo=UTC)
    return parsed


def to_millis(when: _dt.datetime) -> int:
    """Epoch milliseconds; naive datetimes are taken as UTC."""
    if when.tzinfo is None:
        when = when.replace(tzinfo=UTC)
    return int(when.timestamp() * 1000)


#: one-slot memo for format_event_time: bulk imports and server-assigned
#: creation times repeat timestamps heavily (benign racy swap under
#: threads). Keyed on (datetime, utcoffset) — equal instants at different
#: offsets render differently and must not share an entry.
_last_time_fmt: tuple = (None, None, "")


def format_event_time(when: _dt.datetime) -> str:
    """ISO-8601 with millisecond precision and explicit offset."""
    last = _last_time_fmt
    offset = when.utcoffset()
    if last[0] is not None and when == last[0] and offset == last[1]:
        return last[2]
    out = when
    if out.tzinfo is None:
        out = out.replace(tzinfo=UTC)
    text = out.isoformat(timespec="milliseconds")
    globals()["_last_time_fmt"] = (when, offset, text)
    return text


@dataclasses.dataclass(frozen=True)
class Event:
    """One immutable event (``Event.scala:37-55``).

    ``event_id`` is assigned by the event store on insert; ``creation_time``
    records system arrival while ``event_time`` is when the event happened.
    """

    event: str
    entity_type: str
    entity_id: str
    target_entity_type: Optional[str] = None
    target_entity_id: Optional[str] = None
    properties: DataMap = dataclasses.field(default_factory=DataMap)
    event_time: _dt.datetime = dataclasses.field(default_factory=utcnow)
    tags: Sequence[str] = ()
    pr_id: Optional[str] = None
    creation_time: _dt.datetime = dataclasses.field(default_factory=utcnow)
    event_id: Optional[str] = None

    def __post_init__(self):
        if not isinstance(self.properties, DataMap):
            object.__setattr__(self, "properties", DataMap(self.properties))
        object.__setattr__(self, "event_time", _as_datetime(self.event_time))
        object.__setattr__(self, "creation_time", _as_datetime(self.creation_time))
        object.__setattr__(self, "tags", tuple(self.tags))

    # -- JSON codec (wire format of the Event Server, EventJson4sSupport) --
    def to_json_dict(self) -> dict:
        out: dict = {
            "event": self.event,
            "entityType": self.entity_type,
            "entityId": self.entity_id,
            "properties": self.properties.to_dict(),
            "eventTime": format_event_time(self.event_time),
        }
        if self.event_id is not None:
            out["eventId"] = self.event_id
        if self.target_entity_type is not None:
            out["targetEntityType"] = self.target_entity_type
        if self.target_entity_id is not None:
            out["targetEntityId"] = self.target_entity_id
        if self.tags:
            out["tags"] = list(self.tags)
        if self.pr_id is not None:
            out["prId"] = self.pr_id
        out["creationTime"] = format_event_time(self.creation_time)
        return out

    @classmethod
    def from_json_dict(cls, obj: Mapping[str, Any]) -> "Event":
        def req(key: str) -> Any:
            if key not in obj:
                raise EventValidationError(f"field {key} is required")
            return obj[key]

        def req_str(key: str) -> str:
            v = req(key)
            if not isinstance(v, str):
                raise EventValidationError(f"field {key} must be a string")
            return v

        tet = obj.get("targetEntityType")
        if tet is not None and not isinstance(tet, str):
            # ids coerce (numeric ids are common) but TYPE names must be
            # strings — a JSON 0/false here would otherwise surface as an
            # uncaught AttributeError deep in validation (500, not 400)
            raise EventValidationError("field targetEntityType must be a string")
        now = utcnow()
        return cls(
            event=req_str("event"),
            entity_type=req_str("entityType"),
            entity_id=str(req("entityId")),
            target_entity_type=tet,
            target_entity_id=(
                None
                if obj.get("targetEntityId") is None
                else str(obj["targetEntityId"])
            ),
            properties=DataMap(obj.get("properties") or {}),
            event_time=_as_datetime(obj.get("eventTime")) or now,
            tags=tuple(obj.get("tags") or ()),
            pr_id=obj.get("prId"),
            creation_time=_as_datetime(obj.get("creationTime")) or now,
            event_id=obj.get("eventId"),
        )


def idempotency_event_id(app_id: int, key: str) -> str:
    """Deterministic event id for a client-supplied ``idempotencyKey``.

    The dedup mechanism rides the stores' existing upsert-by-``event_id``
    semantics (SQLite ``INSERT OR REPLACE``, the native log's
    last-write-wins replay): same ``(app, key)`` → same id → at most one
    stored event, however many times the POST is retried. That is what
    finally makes *writes* safe to retry on the online path — a retried
    insert with a key can only land on top of itself.
    """
    digest = hashlib.sha256(
        f"{int(app_id)}\x00{key}".encode("utf-8")
    ).hexdigest()
    # "idem" prefix keeps these ids visually distinct from the composite
    # entity-hash/millis/uuid scheme of make_event_id
    return f"idem{digest[:44]}"


def with_event_id(event: Event, event_id: str) -> Event:
    """Copy of ``event`` with ``event_id`` set — the bulk-ingest fast path.

    ``dataclasses.replace`` re-runs ``__init__``/``__post_init__`` (field
    normalization + property validation) per event; on a batch of
    already-validated events that is pure overhead, so this clones the
    instance dict directly. Only safe because Event is frozen (no
    aliasing hazards) and the input was already constructed through
    ``__init__``.
    """
    clone = object.__new__(Event)
    clone.__dict__.update(event.__dict__)
    clone.__dict__["event_id"] = event_id
    return clone


def validate_event(e: Event) -> None:
    """Apply the reference's validation rules (``Event.scala:70-99``).

    Written as plain conditionals (no helper-call/f-string work on the
    valid path): this runs per event on the bulk-ingest hot path.
    """
    if not e.event:
        raise EventValidationError("event must not be empty.")
    if not e.entity_type:
        raise EventValidationError("entityType must not be empty string.")
    if not e.entity_id:
        raise EventValidationError("entityId must not be empty string.")
    tet, tei = e.target_entity_type, e.target_entity_id
    if tet is not None and not tet:
        raise EventValidationError("targetEntityType must not be empty string")
    if tei is not None and not tei:
        raise EventValidationError("targetEntityId must not be empty string.")
    if (tet is None) != (tei is None):
        raise EventValidationError(
            "targetEntityType and targetEntityId must be specified together."
        )
    if is_reserved_prefix(e.event):
        if not is_special_event(e.event):
            raise EventValidationError(
                f"{e.event} is not a supported reserved event name."
            )
        if e.event == "$unset" and e.properties.is_empty():
            raise EventValidationError(
                "properties cannot be empty for $unset event"
            )
        if tet is not None or tei is not None:
            raise EventValidationError(
                f"Reserved event {e.event} cannot have targetEntity"
            )
    if (
        is_reserved_prefix(e.entity_type)
        and e.entity_type not in BUILTIN_ENTITY_TYPES
    ):
        raise EventValidationError(
            f"The entityType {e.entity_type} is not allowed. "
            "'pio_' is a reserved name prefix."
        )
    if (
        tet is not None
        and is_reserved_prefix(tet)
        and tet not in BUILTIN_ENTITY_TYPES
    ):
        raise EventValidationError(
            f"The targetEntityType {tet} is not allowed. "
            "'pio_' is a reserved name prefix."
        )
    for key in e.properties.keyset():
        if is_reserved_prefix(key) and key not in BUILTIN_PROPERTIES:
            raise EventValidationError(
                f"The property {key} is not allowed. "
                "'pio_' is a reserved name prefix."
            )
