"""Model blob stores.

Rebuild of the reference's trained-model persistence
(``data/src/main/scala/io/prediction/data/storage/Models.scala``,
``localfs/LocalFSModels.scala``, ``hdfs/HDFSModels.scala``): an engine
instance's trained models are serialized into a single blob keyed by the
instance id. The reference uses Kryo; here blobs are produced by the workflow
(pickled pytrees / msgpack checkpoints) and the store only moves bytes.
"""

from __future__ import annotations

import abc
import dataclasses
import os
import sqlite3
import threading
import urllib.parse
import zlib
from typing import Optional

from ..utils.durability import fsync_dir


@dataclasses.dataclass(frozen=True)
class Model:
    """``Models.scala``: id (= engine instance id) + opaque bytes."""

    id: str
    models: bytes


class ModelStore(abc.ABC):
    @abc.abstractmethod
    def insert(self, model: Model) -> None: ...

    @abc.abstractmethod
    def get(self, id: str) -> Optional[Model]: ...

    @abc.abstractmethod
    def delete(self, id: str) -> None: ...


class LocalFSModelStore(ModelStore):
    """One file per model id (``localfs/LocalFSModels.scala``), zlib-compressed."""

    def __init__(self, base_dir: str):
        self._base = base_dir
        os.makedirs(base_dir, exist_ok=True)

    def _path(self, id: str) -> str:
        # Percent-encode so distinct ids never collide on one file name.
        safe = urllib.parse.quote(id, safe="")
        return os.path.join(self._base, f"pio_model_{safe}.bin")

    def insert(self, model: Model) -> None:
        # fsync BEFORE the rename, then fsync the directory: without the
        # first, the rename's metadata can journal ahead of the data
        # blocks and a power loss leaves a durable name over a torn blob
        # (proven by testing/crashsim.py in tests/test_crash_consistency);
        # without the second, the new dirent itself may not survive.
        tmp = self._path(model.id) + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(zlib.compress(model.models))
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self._path(model.id))
        fsync_dir(self._base)

    def get(self, id: str) -> Optional[Model]:
        try:
            with open(self._path(id), "rb") as fh:
                return Model(id, zlib.decompress(fh.read()))
        except FileNotFoundError:
            return None

    def delete(self, id: str) -> None:
        try:
            os.remove(self._path(id))
        except FileNotFoundError:
            pass


class SqliteModelStore(ModelStore):
    """Blob table in SQLite — the ES/HDFS-alternative backend."""

    def __init__(self, path: str = ":memory:"):
        if path != ":memory:":
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._lock = threading.RLock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        with self._lock:
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS pio_models "
                "(id TEXT PRIMARY KEY, models BLOB NOT NULL)"
            )
            self._conn.commit()

    def insert(self, model: Model) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO pio_models VALUES (?, ?)",
                (model.id, zlib.compress(model.models)),
            )
            self._conn.commit()

    def get(self, id: str) -> Optional[Model]:
        with self._lock:
            row = self._conn.execute(
                "SELECT models FROM pio_models WHERE id = ?", (id,)
            ).fetchone()
        return Model(id, zlib.decompress(row[0])) if row else None

    def delete(self, id: str) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM pio_models WHERE id = ?", (id,))
            self._conn.commit()
