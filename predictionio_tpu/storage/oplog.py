"""Durable, crash-safe operation log for the storage changefeed.

The replication tier's write-ahead record (the HBase WAL / regionserver
replication-queue analogue, ``docs/storage.md#replication``): every
mutating storage op is assigned a monotonically increasing sequence
number and appended here, and replicas tail the log over
``GET /replicate/changes``.

Record format (little-endian)::

    u64 seq | u32 payload_len | u32 crc32(payload) | payload (JSON, utf-8)

Durability contract — deliberately the same shape as the native event
log's documented contract (``native_events.py``): an append is
acknowledged once the record is in the OS page cache, and the file is
fsync'd every ``sync_every`` appends, on :meth:`sync`, and on
:meth:`close`. A process crash loses nothing already appended (the page
cache survives); a *power* loss can drop or tear the last few records —
on reopen the log is scanned and any torn tail (short header, short
payload, or CRC mismatch) is truncated, so the log always reopens to a
consistent prefix of what was appended. Never weaker than the stores it
feeds: a record that survives is byte-exact, a record that does not was
never claimed durable.

A log directory also carries ``oplog.meta.json`` holding the log's
**generation** (a random id minted at creation — the store-identity
fingerprint replicas use to detect that a primary was wiped or replaced)
and ``base_seq`` (the sequence number *before* the first record, nonzero
when a promoted replica continues a predecessor's numbering). A
partitioned primary's log (``docs/storage.md#partitioning``) also
records its **partition slot** ``[index, count]`` — minted at creation
like the generation, checked loudly on reopen, and surfaced in the
checkpoint so a tailer can prove it is following the partition it was
configured for (tailing the wrong partition's history would silently
diverge the keyspace).
"""

from __future__ import annotations

import json
import logging
import os
import secrets
import struct
import threading
import zlib
from typing import List, Optional, Tuple

from ..utils.durability import atomic_write_bytes

logger = logging.getLogger(__name__)

_HEADER = struct.Struct("<QII")
#: a single logged op should be small (events/metadata) or bounded
#: (base64 model blob); anything beyond this is treated as corruption
_MAX_PAYLOAD = 256 * 1024 * 1024
#: sparse offset index granularity (records between index entries)
_INDEX_EVERY = 64
#: fsync cadence, matching the native event log's ``_SYNC_EVERY``
DEFAULT_SYNC_EVERY = 256

_LOG_NAME = "ops.log"
_META_NAME = "oplog.meta.json"


class OpLogGap(Exception):
    """``read_since`` asked for records older than this log holds (a
    replica fell behind a promoted/truncated primary): the caller must
    full-resync, incremental tailing cannot recover."""


class OpLog:
    """Append-only sequence-numbered op log in one directory."""

    def __init__(
        self,
        directory: str,
        sync_every: int = DEFAULT_SYNC_EVERY,
        base_seq: int = 0,
        partition: Optional[Tuple[int, int]] = None,
    ):
        self._dir = directory
        self._sync_every = max(1, int(sync_every))
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)
        self._path = os.path.join(directory, _LOG_NAME)
        meta = self._load_or_init_meta(base_seq, partition)
        self.generation: str = meta["generation"]
        self.base_seq: int = int(meta["base_seq"])
        #: ``[index, count]`` for a partitioned primary's log, else None
        self.partition: Optional[List[int]] = (
            [int(v) for v in meta["partition"]]
            if meta.get("partition") is not None
            else None
        )
        #: sparse [(seq, byte offset of that record)] every _INDEX_EVERY
        self._index: List[Tuple[int, int]] = []
        self._records = 0
        self._unsynced = 0
        self._failed = False
        self._last_seq, self._size = self._recover()
        # append handle: unbuffered so a completed append is immediately
        # visible to concurrent read_since() calls via the page cache
        self._fh = open(self._path, "ab", buffering=0)

    # -- meta / recovery --------------------------------------------------
    def _load_or_init_meta(
        self, base_seq: int, partition: Optional[Tuple[int, int]]
    ) -> dict:
        path = os.path.join(self._dir, _META_NAME)
        if os.path.exists(path):
            with open(path) as fh:
                meta = json.load(fh)
            if base_seq and int(meta["base_seq"]) != int(base_seq):
                # a caller asking to continue numbering from base_seq must
                # not silently adopt an older log's history — re-promotion
                # over a stale oplog dir would mint already-issued seqs
                raise ValueError(
                    f"oplog {self._dir} starts at base_seq="
                    f"{meta['base_seq']}, caller requires {base_seq}: "
                    "stale log directory, use a fresh one"
                )
            if partition is not None:
                from .partition import check_partition

                # same discipline as base_seq: appending partition k's
                # ops to a log minted for partition j would diverge both
                check_partition(
                    meta.get("partition"), partition[0], partition[1]
                )
                if meta.get("partition") is None:
                    # adopt the slot on a pre-partitioning log (upgrade)
                    meta["partition"] = [int(partition[0]), int(partition[1])]
                    atomic_write_bytes(path, json.dumps(meta).encode())
            return meta
        meta = {"generation": secrets.token_hex(8), "base_seq": int(base_seq)}
        if partition is not None:
            meta["partition"] = [int(partition[0]), int(partition[1])]
        atomic_write_bytes(path, json.dumps(meta).encode())
        return meta

    def _recover(self) -> Tuple[int, int]:
        """Scan the log, build the sparse index, truncate any torn tail.
        Returns (last_seq, valid_size)."""
        last_seq = self.base_seq
        offset = 0
        try:
            size = os.path.getsize(self._path)
        except OSError:
            return last_seq, 0
        with open(self._path, "rb") as fh:
            while offset + _HEADER.size <= size:
                header = fh.read(_HEADER.size)
                if len(header) < _HEADER.size:
                    break
                seq, length, crc = _HEADER.unpack(header)
                if (
                    length > _MAX_PAYLOAD
                    or offset + _HEADER.size + length > size
                    or seq != last_seq + 1
                ):
                    break
                payload = fh.read(length)
                if len(payload) < length or zlib.crc32(payload) != crc:
                    break
                if self._records % _INDEX_EVERY == 0:
                    self._index.append((seq, offset))
                self._records += 1
                last_seq = seq
                offset += _HEADER.size + length
        if offset < size:
            # torn tail (power loss mid-append): truncate to the last
            # complete record so the durability contract's "consistent
            # prefix" invariant holds on every reopen
            logger.warning(
                "oplog %s: truncating torn tail (%d -> %d bytes)",
                self._path, size, offset,
            )
            with open(self._path, "r+b") as fh:
                fh.truncate(offset)
                fh.flush()
                os.fsync(fh.fileno())
        return last_seq, offset

    # -- introspection ----------------------------------------------------
    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._last_seq

    @property
    def oldest_seq(self) -> int:
        """First sequence number this log can serve (base_seq + 1)."""
        return self.base_seq + 1

    def adopt_slot(self, index: int, count: int) -> None:
        """Claim partition slot ``[index, count]`` for this log — the
        live-migration upgrade path (docs/storage.md#live-migration)
        where an empty log minted before the new layout existed joins
        it. Only legal while the log has served nothing: re-sloting a
        log with history would let a tailer resume a cursor minted
        against a different keyspace split. A matching existing slot is
        a no-op; a conflicting one, or any history, is loud."""
        with self._lock:
            if self.partition is not None:
                from .partition import check_partition

                check_partition(self.partition, index, count)
                return
            if self._last_seq != self.base_seq:
                raise ValueError(
                    f"oplog {self._dir} has history through seq "
                    f"{self._last_seq}; cannot adopt partition slot "
                    f"[{index}, {count}] — use a fresh log directory"
                )
            path = os.path.join(self._dir, _META_NAME)
            with open(path) as fh:
                meta = json.load(fh)
            meta["partition"] = [int(index), int(count)]
            # pio: lint-ok[flow-blocking-under-lock] one-shot admin op on a provably empty log; the slot must be durable before any append can observe it
            atomic_write_bytes(path, json.dumps(meta).encode())
            self.partition = [int(index), int(count)]

    def checkpoint(self) -> dict:
        """The ``/replicate/checkpoint`` identity triple (plus the
        partition slot when this is a partitioned primary's log)."""
        with self._lock:
            out = {
                "seq": self._last_seq,
                "generation": self.generation,
                "oldestSeq": self.oldest_seq,
            }
            if self.partition is not None:
                out["partition"] = list(self.partition)
            return out

    # -- append -----------------------------------------------------------
    def append(self, op: dict) -> int:
        """Append one op, returning its sequence number. One ``write(2)``
        per record (header+payload as a single buffer), so a torn append
        can only ever tear the *tail* record."""
        payload = json.dumps(op, separators=(",", ":")).encode("utf-8")
        with self._lock:
            if self._failed:
                raise OSError(
                    f"oplog {self._path} is failed (earlier torn append "
                    "could not be rolled back); restart to recover"
                )
            seq = self._last_seq + 1
            record = (
                _HEADER.pack(seq, len(payload), zlib.crc32(payload)) + payload
            )
            view = memoryview(record)
            try:
                while view:  # raw (unbuffered) writes may be partial
                    view = view[self._fh.write(view):]
            except Exception:
                # A partial append (ENOSPC mid-record) would desync the
                # file from _size/_index and corrupt every later record.
                # Roll the file back to the last whole record; if even
                # that fails, poison the log rather than corrupt it.
                try:
                    os.ftruncate(self._fh.fileno(), self._size)
                except OSError:
                    self._failed = True
                raise
            if self._records % _INDEX_EVERY == 0:
                self._index.append((seq, self._size))
            self._records += 1
            self._last_seq = seq
            self._size += len(record)
            self._unsynced += 1
            if self._unsynced >= self._sync_every:
                # pio: lint-ok[conc-blocking-under-lock] the fsync IS the critical section: acks must not reorder against appends, so durability happens under the same lock
                os.fsync(self._fh.fileno())
                self._unsynced = 0
            return seq

    def sync(self) -> None:
        with self._lock:
            if self._fh is not None:
                # pio: lint-ok[conc-blocking-under-lock] durability barrier: a concurrent append must not land between the fsync and the cadence reset
                os.fsync(self._fh.fileno())
                self._unsynced = 0

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                # pio: lint-ok[conc-blocking-under-lock] final durability barrier before the handle dies; nothing else can need this lock afterwards
                os.fsync(self._fh.fileno())
                self._fh.close()
                self._fh = None

    # -- read -------------------------------------------------------------
    def read_since(
        self, since: int, limit: int = 500
    ) -> Tuple[List[Tuple[int, dict]], int]:
        """Up to ``limit`` records with seq > ``since``, plus the log's
        current last_seq. Raises :class:`OpLogGap` when ``since`` predates
        this log's oldest record (the caller must resync)."""
        with self._lock:
            last_seq, committed = self._last_seq, self._size
            if since < self.base_seq:
                raise OpLogGap(
                    f"oplog holds seq > {self.base_seq}, asked since={since}"
                )
            # nearest index entry at or before the first wanted record
            offset = 0
            for seq, off in self._index:
                if seq <= since + 1:
                    offset = off
                else:
                    break
        out: List[Tuple[int, dict]] = []
        if since >= last_seq or limit <= 0:
            return out, last_seq
        with open(self._path, "rb") as fh:
            fh.seek(offset)
            while offset + _HEADER.size <= committed and len(out) < limit:
                seq, length, _crc = _HEADER.unpack(fh.read(_HEADER.size))
                payload = fh.read(length)
                offset += _HEADER.size + length
                if seq <= since:
                    continue
                out.append((seq, json.loads(payload)))
        return out, last_seq
