"""Bidirectional maps for ID ↔ index translation.

Rebuild of the reference's ``BiMap`` / ``EntityMap``
(``data/src/main/scala/io/prediction/data/storage/BiMap.scala:25-164``,
``EntityMap.scala``): the device every recommender template uses to turn
string entity IDs into dense matrix indices and back. On TPU this is the
boundary between host-side string IDs and device-side integer indices: the
forward map feeds index arrays to infeed, the inverse map decodes top-k
results coming back from the scoring kernel.
"""

from __future__ import annotations

from typing import Dict, Generic, Iterable, Iterator, List, Mapping, Optional, Tuple, TypeVar

import numpy as np

K = TypeVar("K")
V = TypeVar("V")


class BiMap(Generic[K, V]):
    """Immutable bidirectional map (``BiMap.scala:25-105``).

    Construction fails if values are not unique, matching the reference's
    requirement that the map be invertible.
    """

    def __init__(self, forward: Mapping[K, V], _inverse: Optional[Mapping[V, K]] = None):
        self._forward: Dict[K, V] = dict(forward)
        if _inverse is None:
            inverse: Dict[V, K] = {}
            for k, v in self._forward.items():
                if v in inverse:
                    raise ValueError(
                        f"BiMap values must be unique; duplicate value {v!r}"
                    )
                inverse[v] = k
            self._inverse = inverse
        else:
            self._inverse = dict(_inverse)

    # -- accessors --------------------------------------------------------
    def __getitem__(self, key: K) -> V:
        return self._forward[key]

    def get(self, key: K) -> Optional[V]:
        return self._forward.get(key)

    def get_or_else(self, key: K, default: V) -> V:
        return self._forward.get(key, default)

    def __contains__(self, key: K) -> bool:
        return key in self._forward

    def __len__(self) -> int:
        return len(self._forward)

    def __iter__(self) -> Iterator[K]:
        return iter(self._forward)

    def contains(self, key: K) -> bool:
        return key in self._forward

    @property
    def inverse(self) -> "BiMap[V, K]":
        """O(1) inverted view (``BiMap.scala:45-50``)."""
        return BiMap(self._inverse, _inverse=self._forward)

    def to_dict(self) -> Dict[K, V]:
        return dict(self._forward)

    def take(self, n: int) -> "BiMap[K, V]":
        sub = dict(list(self._forward.items())[:n])
        return BiMap(sub)

    def __eq__(self, other: object) -> bool:
        if isinstance(other, BiMap):
            return self._forward == other._forward
        return NotImplemented

    def __hash__(self) -> int:
        return hash(frozenset(self._forward.items()))

    def __repr__(self) -> str:
        return f"BiMap({self._forward!r})"

    # -- builders (BiMap.scala:110-164) -----------------------------------
    @staticmethod
    def string_int(keys: Iterable[str]) -> "BiMap[str, int]":
        """Distinct keys → dense [0, n) indices (``BiMap.stringInt``)."""
        seen: Dict[str, int] = {}
        for k in keys:
            if k not in seen:
                seen[k] = len(seen)
        return BiMap(seen)

    string_long = string_int  # Python ints are unbounded

    # -- vectorized translation (TPU infeed path) --------------------------
    def map_array(
        self, keys: Iterable[K], missing: int = -1
    ) -> np.ndarray:
        """Vectorized forward lookup → int32 numpy array.

        Unknown keys map to ``missing`` so callers can mask them out before
        device transfer (the sparse-infeed analogue of the reference's
        ``.filter`` on map hits).
        """
        fwd = self._forward
        return np.fromiter(
            (fwd.get(k, missing) for k in keys), dtype=np.int32
        )

    def inverse_list(self, indices: Iterable[V]) -> List[K]:
        inv = self._inverse
        return [inv[i] for i in indices]


class EntityMap(BiMap[str, int]):
    """BiMap from entity id → dense index that also carries entity payloads
    (``EntityMap.scala``)."""

    def __init__(self, entities: Mapping[str, object]):
        ids = BiMap.string_int(entities.keys())
        super().__init__(ids.to_dict())
        self._entities = dict(entities)

    def entity(self, key: str):
        return self._entities[key]

    def entity_by_index(self, index: int):
        return self._entities[self._inverse[index]]
