"""Bidirectional maps for ID ↔ index translation.

Rebuild of the reference's ``BiMap`` / ``EntityMap``
(``data/src/main/scala/io/prediction/data/storage/BiMap.scala:25-164``,
``EntityMap.scala``): the device every recommender template uses to turn
string entity IDs into dense matrix indices and back. On TPU this is the
boundary between host-side string IDs and device-side integer indices: the
forward map feeds index arrays to infeed, the inverse map decodes top-k
results coming back from the scoring kernel.
"""

from __future__ import annotations

from typing import Dict, Generic, Iterable, Iterator, List, Mapping, Optional, TypeVar

import numpy as np

K = TypeVar("K")
V = TypeVar("V")


class BiMap(Generic[K, V]):
    """Immutable bidirectional map (``BiMap.scala:25-105``).

    Construction fails if values are not unique, matching the reference's
    requirement that the map be invertible.
    """

    def __init__(self, forward: Mapping[K, V], _inverse: Optional[Mapping[V, K]] = None):
        self._forward: Dict[K, V] = dict(forward)
        if _inverse is None:
            inverse: Dict[V, K] = {}
            for k, v in self._forward.items():
                if v in inverse:
                    raise ValueError(
                        f"BiMap values must be unique; duplicate value {v!r}"
                    )
                inverse[v] = k
            self._inverse = inverse
        else:
            self._inverse = dict(_inverse)
        self._inverse_view: Optional["BiMap[V, K]"] = None

    # -- accessors --------------------------------------------------------
    def __getitem__(self, key: K) -> V:
        return self._forward[key]

    def get(self, key: K) -> Optional[V]:
        return self._forward.get(key)

    def get_or_else(self, key: K, default: V) -> V:
        return self._forward.get(key, default)

    def __contains__(self, key: K) -> bool:
        return key in self._forward

    def __len__(self) -> int:
        return len(self._forward)

    def __iter__(self) -> Iterator[K]:
        return iter(self._forward)

    def contains(self, key: K) -> bool:
        return key in self._forward

    @property
    def inverse(self) -> "BiMap[V, K]":
        """O(1) inverted view (``BiMap.scala:45-50``).

        Cached and dict-sharing: the first access builds a view object
        whose forward/inverse ARE this map's dicts (BiMaps are
        never mutated after construction), so serving-path code can take
        ``.inverse`` per query without copying the catalog."""
        inv = self._inverse_view
        if inv is None:
            inv = BiMap.__new__(BiMap)
            inv._forward = self._inverse
            inv._inverse = self._forward
            # deliberately NOT a back-pointer to self: a map↔view cycle
            # would keep catalog-sized dicts alive past refcount zero
            # (until a gen-2 gc) when a deployment is dropped on /reload.
            # Chaining .inverse.inverse just builds another shared-dict
            # view — equal, not identical.
            inv._inverse_view = None
            self._inverse_view = inv
        return inv

    def __getstate__(self):
        # the view is a cheap derived cache; keep persisted blobs lean
        state = dict(self.__dict__)
        state.pop("_inverse_view", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._inverse_view = None

    def to_dict(self) -> Dict[K, V]:
        return dict(self._forward)

    def take(self, n: int) -> "BiMap[K, V]":
        sub = dict(list(self._forward.items())[:n])
        return BiMap(sub)

    def __eq__(self, other: object) -> bool:
        if isinstance(other, BiMap):
            return self._forward == other._forward
        return NotImplemented

    def __hash__(self) -> int:
        return hash(frozenset(self._forward.items()))

    def __repr__(self) -> str:
        return f"BiMap({self._forward!r})"

    # -- builders (BiMap.scala:110-164) -----------------------------------
    @staticmethod
    def string_int(keys: Iterable[str]) -> "BiMap[str, int]":
        """Distinct keys → dense [0, n) indices (``BiMap.stringInt``)."""
        seen: Dict[str, int] = {}
        for k in keys:
            if k not in seen:
                seen[k] = len(seen)
        return BiMap(seen)

    string_long = string_int  # Python ints are unbounded

    # -- vectorized translation (TPU infeed path) --------------------------
    def map_array(
        self, keys: Iterable[K], missing: int = -1
    ) -> np.ndarray:
        """Vectorized forward lookup → int32 numpy array.

        Unknown keys map to ``missing`` so callers can mask them out before
        device transfer (the sparse-infeed analogue of the reference's
        ``.filter`` on map hits).
        """
        fwd = self._forward
        return np.fromiter(
            (fwd.get(k, missing) for k in keys), dtype=np.int32
        )

    def inverse_list(self, indices: Iterable[V]) -> List[K]:
        inv = self._inverse
        return [inv[i] for i in indices]


class HashedIdMap:
    """Fixed-capacity hashed ID → index map for huge ID spaces.

    The exact :class:`BiMap` costs ~194 bytes per unique id on the host
    (measured: 5M ids → 970 MB for the forward+inverse dicts and their key
    strings), so a billion-entity catalog needs ~190 GB — the host-memory
    wall SURVEY §7 flags. This map stores **nothing per id**: an id's index
    is ``fnv1a64(id, salt) & (capacity - 1)`` (the hashing trick), computed
    natively in batch (``native/idhash.cc``), so memory is O(1) on the host
    and ``capacity × rank × 4`` bytes for the factor table on device.

    Trade-offs, stated plainly:

    * **Collisions alias entities.** The fraction of ids sharing a slot
      with some other id is ≈ ``1 − exp(−n / capacity)``; size capacity ≥
      16n to keep aliasing under ~6 % (≥ 8n gives ~12 %). Aliased entities
      share a factor row (their ratings merge) — acceptable for the *query
      side* of a recommender (a user's own id is supplied at query time),
      not for the *result side*.
    * **Capacity tops out at 2³¹** (indices are int32, and a factor table
      cannot exceed 2³¹ rows anyway). Beyond ~10⁸ entities, shard the id
      space across hosts — each host hashes its shard into its own factor
      shard — rather than growing one map.
    * **Capacity is also the factor-table row count** downstream: training
      allocates O(capacity) device memory (capacity × rank × 4 B) and
      O(capacity) small host arrays in bucketize (~12 B/slot), so size
      capacity to what a device can hold (e.g. ≤ 2²⁷ rows at rank 50 on a
      16 GB chip), not to the raw id-space size.
    * **No inverse.** Decoded results need id strings back, so keep the
      exact BiMap for the smaller side (items). ``inverse`` raises.

    Interface-compatible with BiMap where forward-only semantics make
    sense (``map_array``, ``__getitem__``, ``get``, ``__len__`` = capacity).
    """

    _MAX_CAPACITY = 1 << 31

    def __init__(self, capacity: int, salt: int = 0):
        if capacity <= 0 or capacity & (capacity - 1):
            raise ValueError(f"capacity must be a power of two, got {capacity}")
        if capacity > self._MAX_CAPACITY:
            raise ValueError(
                f"capacity {capacity} exceeds 2^31 (int32 indices); shard "
                "the id space across hosts instead of growing one map"
            )
        self.capacity = capacity
        self.salt = salt

    def __len__(self) -> int:
        return self.capacity

    def __getitem__(self, key: str) -> int:
        return int(self.map_array([key])[0])

    def get(self, key: str) -> int:
        # every key hashes somewhere — a hashed map has no "unknown id"
        return self[key]

    def __contains__(self, key: str) -> bool:
        return True

    @property
    def inverse(self):
        raise TypeError(
            "HashedIdMap cannot be inverted (indices do not decode to ids);"
            " use an exact BiMap for the side whose ids must be recovered"
        )

    def expected_collision_fraction(self, n_ids: int) -> float:
        """Fraction of ids expected to share a slot with some other id
        (≈ 1 − exp(−n/capacity) for n ids thrown into capacity slots)."""
        import math

        return 1.0 - math.exp(-n_ids / self.capacity)

    def map_array(self, keys, missing: int = -1) -> np.ndarray:
        """Vectorized hash-index of a chunk of string ids (native batch
        fnv1a64; pure-Python fallback on toolchain-less hosts).

        ``missing`` exists for BiMap signature compatibility but is a
        no-op: a hashed map has no unknown keys — every id hashes to a
        valid slot, so callers cannot mask out never-trained ids.
        """
        keys = list(keys)
        if not keys:
            return np.zeros(0, dtype=np.int32)
        hashes = _fnv1a64_batch(keys, self.salt)
        return (hashes & np.uint64(self.capacity - 1)).astype(np.int32)


#: Latched after the first failed native-idhash build (per process), so a
#: toolchain-less host pays one compiler attempt, not one per chunk.
_NATIVE_IDHASH_BROKEN = False


def _fnv1a64_batch(keys, salt: int) -> np.ndarray:
    global _NATIVE_IDHASH_BROKEN
    encoded = [k.encode("utf-8") for k in keys]
    if not _NATIVE_IDHASH_BROKEN:
        from ..native import NativeBuildError

        try:
            return _fnv1a64_batch_native(encoded, salt)
        except NativeBuildError as exc:
            import logging

            logging.getLogger(__name__).warning(
                "native idhash unavailable, using (slow) Python hashing: %s",
                exc,
            )
            _NATIVE_IDHASH_BROKEN = True
    # pure-Python fnv1a64 (same constants as native/idhash.cc)
    out = np.empty(len(encoded), dtype=np.uint64)
    mask = (1 << 64) - 1
    for j, data in enumerate(encoded):
        h = 14695981039346656037 ^ salt
        for b in data:
            h = ((h ^ b) * 1099511628211) & mask
        out[j] = h if h else 1
    return out


def _fnv1a64_batch_native(encoded, salt: int) -> np.ndarray:
    import ctypes

    from ..native import load_library

    lib = load_library("idhash")
    if not getattr(lib, "_pio_configured", False):
        lib.pio_fnv1a64_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_uint64, ctypes.c_void_p,
        ]
        lib._pio_configured = True
    buf = np.frombuffer(b"".join(encoded), dtype=np.uint8)
    ends = np.cumsum([len(e) for e in encoded], dtype=np.int64)
    out = np.empty(len(encoded), dtype=np.uint64)
    lib.pio_fnv1a64_batch(
        buf.ctypes.data_as(ctypes.c_void_p),
        ends.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(len(encoded)),
        ctypes.c_uint64(salt),
        out.ctypes.data_as(ctypes.c_void_p),
    )
    return out


class EntityMap(BiMap[str, int]):
    """BiMap from entity id → dense index that also carries entity payloads
    (``EntityMap.scala``)."""

    def __init__(self, entities: Mapping[str, object]):
        ids = BiMap.string_int(entities.keys())
        super().__init__(ids.to_dict())
        self._entities = dict(entities)

    def entity(self, key: str):
        return self._entities[key]

    def entity_by_index(self, index: int):
        return self._entities[self._inverse[index]]
