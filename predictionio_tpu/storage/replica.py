"""Warm-standby storage replica: changefeed tailing, read serving,
promotion.

The availability half of the replication tier (``docs/storage.md``):
a :class:`StorageReplica` owns its *own* local stores and keeps them
converged with a primary by tailing ``GET /replicate/changes`` —
sequence-keyed, idempotent replay (``changefeed.apply_op``), so replays
after a replica crash are harmless. It serves every read route of the
storage API (replicas double as read capacity for training scans),
rejects mutations with ``409`` + a primary hint, and reports lag on
``GET /status.json``.

Read-your-writes: a read carrying ``X-PIO-Min-Seq`` (the client's last
acked write seq) is held for up to ``catchup_wait_s`` waiting for the
tailer to apply that seq, then answered ``409`` with the applied seq —
wait-or-reject, never a silently stale answer.

Progress durability: ``applied.json`` in ``state_dir`` records the seq
the local stores have durably applied through, written crash-safely
(``utils/durability.atomic_write_bytes``) *after* each applied batch. A
crash between apply and marker write means the marker under-reports —
the tailer then re-fetches and re-applies a suffix, which idempotent
replay absorbs.

**Promotion** (warm-standby failover): :meth:`StorageReplica.promote`
stops the tailer and attaches a fresh changefeed whose numbering
*continues* from the applied seq (``OpLog(base_seq=applied)``), so
client seq tokens issued by the old primary stay meaningful against the
new one. The oplog generation changes — surviving replicas of the dead
primary must resync rather than silently tail a diverged history.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable, Optional

from ..utils.durability import atomic_write_bytes
from .changefeed import Changefeed, apply_op
from .metadata import MetadataStore
from .oplog import OpLog
from .storage_server import StorageServer

logger = logging.getLogger(__name__)

_APPLIED_NAME = "applied.json"


class ReplicationError(Exception):
    """The changefeed cannot be tailed incrementally any further:
    generation mismatch (primary wiped/replaced) or sequence gap (this
    replica fell behind a truncated/promoted log). Requires a resync."""


class ReplicaTailer:
    """Pulls the primary's changefeed into local stores.

    Single-threaded by contract: call :meth:`step` from one place (the
    replica's poll loop, or a test driving it deterministically)."""

    def __init__(
        self,
        primary_url: str,
        events,
        metadata: MetadataStore,
        models,
        state_dir: str,
        timeout: float = 30.0,
        batch_limit: int = 500,
        partition: Optional[tuple] = None,
    ):
        self._primary = primary_url.rstrip("/")
        self._events = events
        self._metadata = metadata
        self._models = models
        self._state_dir = state_dir
        self._timeout = timeout
        self._batch_limit = batch_limit
        #: declared ``(index, count)`` slot — checked against the
        #: primary's own declaration on every batch so a replica can
        #: never silently converge on the wrong partition's history
        self.partition: Optional[tuple] = (
            (int(partition[0]), int(partition[1]))
            if partition is not None and int(partition[1]) > 1
            else None
        )
        #: serializes the apply phase against promotion: promote() takes
        #: this lock after stopping the poll loop, so a batch already
        #: fetched from the dying primary can never apply *after* the
        #: node started accepting its own writes
        self.apply_lock = threading.Lock()
        #: checked (under apply_lock) before applying a fetched batch
        self.aborted: Callable[[], bool] = lambda: False
        os.makedirs(state_dir, exist_ok=True)
        self._applied_path = os.path.join(state_dir, _APPLIED_NAME)
        self.applied_seq = 0
        self.generation: Optional[str] = None
        self.primary_seq: Optional[int] = None  # last observed, for lag
        self.last_error: Optional[str] = None
        self._load_applied()

    # -- progress marker --------------------------------------------------
    def _load_applied(self) -> None:
        try:
            with open(self._applied_path) as fh:
                state = json.load(fh)
            self.applied_seq = int(state["seq"])
            self.generation = state.get("generation")
        except (OSError, ValueError, KeyError):
            self.applied_seq = 0
            self.generation = None

    def _persist_applied(self) -> None:
        atomic_write_bytes(
            self._applied_path,
            json.dumps(
                {"seq": self.applied_seq, "generation": self.generation}
            ).encode(),
        )

    # -- tailing ----------------------------------------------------------
    def _fetch(self) -> dict:
        from .remote import RemoteStorageError, _json, _request

        url = (
            f"{self._primary}/replicate/changes"
            f"?since={self.applied_seq}&limit={self._batch_limit}"
        )
        try:
            with _request(url, timeout=self._timeout) as resp:
                return _json(resp)
        except RemoteStorageError as exc:
            if exc.code == 410:
                raise ReplicationError(
                    f"changefeed gap at seq {self.applied_seq}: {exc}"
                ) from exc
            raise

    def lag(self) -> Optional[int]:
        """Ops behind the last observed primary seq (None before the
        first successful fetch)."""
        if self.primary_seq is None:
            return None
        return max(0, self.primary_seq - self.applied_seq)

    def step(self) -> int:
        """One fetch+apply round; returns the number of ops applied.
        Transport errors propagate (the poll loop logs and retries);
        :class:`ReplicationError` means incremental tailing is over."""
        batch = self._fetch()
        with self.apply_lock:
            if self.aborted():
                return 0  # promotion won the race: drop the fetched batch
            if self.partition is not None:
                from .partition import check_partition

                try:
                    check_partition(
                        batch.get("partition"),
                        self.partition[0], self.partition[1],
                    )
                except ValueError as exc:
                    raise ReplicationError(str(exc)) from exc
            generation = batch.get("generation")
            if self.generation is None:
                self.generation = generation
            elif generation != self.generation:
                raise ReplicationError(
                    f"primary generation changed ({self.generation} -> "
                    f"{generation}): store was replaced, resync required"
                )
            self.primary_seq = int(batch["lastSeq"])
            if self.primary_seq < self.applied_seq:
                # Same generation but the primary's history ENDS before
                # our applied seq: a post-power-loss restart truncated
                # records we already consumed from its page cache, and
                # any seqs it re-mints will carry different ops. Silent
                # `seq <= applied` skipping would diverge forever — this
                # must be as loud as a generation change.
                raise ReplicationError(
                    f"primary seq {self.primary_seq} behind applied "
                    f"{self.applied_seq} under generation "
                    f"{self.generation}: primary history rewound "
                    "(post-crash truncation), resync required"
                )
            applied = 0
            for entry in batch.get("changes", []):
                seq = int(entry["seq"])
                if seq <= self.applied_seq:
                    continue  # idempotent replay keyed on seq
                apply_op(
                    entry["op"], self._events, self._metadata, self._models
                )
                self.applied_seq = seq
                applied += 1
            if applied:
                self._persist_applied()
            elif self.generation is not None and not os.path.exists(
                self._applied_path
            ):
                self._persist_applied()  # pin the generation before op 1
            return applied

    def catch_up(self, max_rounds: int = 10_000) -> int:
        """Drain the feed until the replica matches the primary's current
        seq; returns the final applied seq. Deterministic (no sleeps)."""
        for _ in range(max_rounds):
            self.step()
            if self.primary_seq is not None and self.applied_seq >= self.primary_seq:
                return self.applied_seq
        raise ReplicationError(
            f"no convergence after {max_rounds} rounds "
            f"(applied {self.applied_seq}, primary {self.primary_seq})"
        )


class StorageReplica(StorageServer):
    """Read-only storage server converging on a primary's changefeed."""

    accepts_writes = False
    service_name = "storage-replica"

    def __init__(
        self,
        host: str,
        port: int,
        events,
        metadata: MetadataStore,
        models,
        primary_url: str,
        state_dir: str,
        catchup_wait_s: float = 2.0,
        timeout: float = 30.0,
        partition: Optional[tuple] = None,
    ):
        super().__init__(
            host, port, events, metadata, models, changefeed=None,
            partition=partition,
        )
        self.primary_url = primary_url.rstrip("/")
        self.catchup_wait_s = catchup_wait_s
        self.tailer = ReplicaTailer(
            self.primary_url, events, metadata, models, state_dir,
            timeout=timeout, partition=partition,
        )
        self.tailer.aborted = lambda: self._stop_polling.is_set()
        self._applied_cond = threading.Condition()
        self._poll_thread: Optional[threading.Thread] = None
        self._stop_polling = threading.Event()
        # Replication lag in ops, pulled at scrape time: the fleet alarm
        # for a stalling tailer. A promoted replica is the primary — by
        # definition caught up with itself — so the gauge pins to 0 after
        # failover (the loadgen chaos scenario asserts exactly this).
        # Labeled by partition slot so the SLO plane's freshness
        # objective evaluates each partition's chain independently — one
        # lagging partition must never hide behind a healthy fleet mean
        # (docs/slo.md).
        self.metrics.gauge_callback(
            "pio_replication_lag_ops",
            self.replication_lag,
            "Ops behind the last observed primary seq (0 = caught up)",
            # pio: lint-ok[obs-unbounded-label] the partition index is this node's own configured slot — one value per process, a closed 0..N-1 vocabulary fleet-wide
            labels={"partition": str(self.partition[0])},
        )

    def replication_lag(self) -> int:
        """Current lag in ops; 0 when promoted or before the first fetch
        (no observation is indistinguishable from caught-up — the tailer
        error string in ``/status.json`` disambiguates)."""
        if self.accepts_writes:
            return 0
        lag = self.tailer.lag()
        return 0 if lag is None else lag

    # -- replication hooks ------------------------------------------------
    def applied_seq(self) -> int:
        if self.changefeed is not None:  # promoted
            return self.changefeed.last_seq
        return self.tailer.applied_seq

    def wait_for_seq(self, min_seq: int, deadline=None) -> bool:
        """Bounded wait for the tailer to apply ``min_seq`` (notified per
        batch). The bound is ``catchup_wait_s`` capped by the request
        deadline — wait-or-reject, never an unbounded hold."""
        if self.applied_seq() >= min_seq:
            return True
        budget = self.catchup_wait_s
        if deadline is not None:
            budget = min(budget, max(0.0, deadline.remaining_s()))
        end = time.monotonic() + budget
        with self._applied_cond:
            while self.applied_seq() < min_seq:
                remaining = end - time.monotonic()
                if remaining <= 0:
                    return False
                self._applied_cond.wait(remaining)
        return True

    def step(self) -> int:
        """One deterministic tail round (tests and the poll loop)."""
        applied = self.tailer.step()
        if applied:
            with self._applied_cond:
                self._applied_cond.notify_all()
        return applied

    def catch_up(self) -> int:
        seq = self.tailer.catch_up()
        with self._applied_cond:
            self._applied_cond.notify_all()
        return seq

    # -- background polling ----------------------------------------------
    def start_tailing(
        self,
        poll_interval_s: float = 0.5,
        sleep: Callable[[float], None] = time.sleep,
    ) -> threading.Thread:
        """Poll the primary in a daemon thread. Transport errors are
        logged and retried on the next interval (the primary being down
        is the replica's *reason to exist*, not a crash); a
        :class:`ReplicationError` stops tailing and is surfaced in
        ``/status.json``."""

        watchdog = self.health.watchdog if self.health is not None else None
        if watchdog is not None:
            # a tailer that stops looping ENTIRELY (wedged fetch, stuck
            # apply) is a stall even while pio_replication_lag_ops reads
            # its last value — the beat watches the loop, not the lag
            watchdog.expect(
                "replica.tail", max_gap_s=max(60.0, poll_interval_s * 40)
            )

        def loop() -> None:
            while not self._stop_polling.is_set():
                if watchdog is not None:
                    watchdog.beat("replica.tail")
                try:
                    applied = self.step()
                    self.tailer.last_error = None
                except ReplicationError as exc:
                    self.tailer.last_error = str(exc)
                    logger.error("replica tailing stopped: %s", exc)
                    return
                except Exception as exc:
                    if str(exc) != self.tailer.last_error:
                        # log on state change only, not once per poll —
                        # a dead primary for an hour is one line, not 7200
                        logger.warning("replica tail fetch failed: %s", exc)
                    self.tailer.last_error = str(exc)
                    applied = 0
                if applied == 0:
                    sleep(poll_interval_s)

        self._poll_thread = threading.Thread(target=loop, daemon=True)
        self._poll_thread.start()
        return self._poll_thread

    def stop_tailing(self) -> None:
        self._stop_polling.set()
        if self.health is not None:
            # a deliberately stopped tailer is not a stall
            self.health.watchdog.unexpect("replica.tail")

    # -- failover ---------------------------------------------------------
    def promote(self, oplog_dir: Optional[str] = None) -> dict:
        """Become the primary: stop tailing, attach a fresh changefeed
        continuing this replica's applied sequence numbering, accept
        writes. Returns the new role status. Idempotent — promoting an
        already-promoted replica is a no-op."""
        if self.accepts_writes:
            return self.status_json()
        self.stop_tailing()
        # Take the apply gate: a batch already fetched from the dying
        # primary must either finish applying NOW or be dropped (the
        # tailer re-checks `aborted` under this lock) — never land after
        # this node starts acking its own writes.
        with self.tailer.apply_lock:
            applied = self.tailer.applied_seq
            if oplog_dir is None:
                # applied-seq-suffixed dir: re-promotion at a different
                # seq can never silently reuse a stale sequence history
                # (OpLog also refuses a base_seq mismatch loudly)
                oplog_dir = os.path.join(
                    self.tailer._state_dir, f"oplog-{applied}"
                )
            self.changefeed = Changefeed(
                OpLog(
                    oplog_dir, base_seq=applied,
                    # the promoted log keeps the dead primary's keyspace
                    # slot: clients and tailers of partition k keep
                    # talking to partition k, just at a new address
                    partition=(
                        self._partition if self._partition[1] > 1 else None
                    ),
                ),
                self.events, self.metadata, self.models,
            )
            self.accepts_writes = True
            self.primary_url = None
        with self._applied_cond:
            self._applied_cond.notify_all()  # release any waiting reads
        from ..obs.flight import record as flight_record

        flight_record("promote", "replica.promote", appliedSeq=applied)
        logger.info("replica promoted to primary at seq %d", applied)
        return self.status_json()

    def checkpoint_json(self) -> Optional[dict]:
        """Replicas answer the freshness probe from their applied state
        (no changefeed exists until promotion)."""
        if self.changefeed is not None:  # promoted
            return super().checkpoint_json()
        out = {
            "seq": self.tailer.applied_seq,
            "generation": self.tailer.generation,
            "replica": True,
        }
        if self.tailer.partition is not None:
            out["partition"] = list(self.tailer.partition)
        return out

    def replication_json(self) -> dict:
        out = super().replication_json()
        if not self.accepts_writes:
            row = out["partitions"][0]
            row["primary"] = self.primary_url
            lag = self.tailer.lag()
            if lag is not None:
                row["lag"] = lag
        return out

    def status_json(self) -> dict:
        out = super().status_json()
        if self.accepts_writes:
            return out  # promoted: plain primary status
        out["appliedSeq"] = self.tailer.applied_seq
        out["primary"] = self.primary_url
        lag = self.tailer.lag()
        if lag is not None:
            out["lag"] = lag
        if self.tailer.last_error:
            out["lastError"] = self.tailer.last_error
        return out


def create_storage_replica(
    host: str,
    port: int,
    primary_url: str,
    registry=None,
    state_dir: Optional[str] = None,
    partition_index: int = 0,
    partition_count: int = 1,
) -> StorageReplica:
    """Build a replica fronting ``registry``'s local stores (the ``pio
    storageserver --replica-of URL`` entry point).
    ``partition_index``/``partition_count`` declare which keyspace slot
    the tailed primary must own (docs/storage.md#partitioning) — a slot
    mismatch stops tailing loudly instead of converging on the wrong
    partition's history."""
    if registry is None:
        from .registry import get_registry

        registry = get_registry()
    if state_dir is None:
        from .registry import base_dir

        state_dir = os.path.join(base_dir(), "replica_state")
    return StorageReplica(
        host,
        port,
        registry.get_events(),
        registry.get_metadata(),
        registry.get_models(),
        primary_url,
        state_dir,
        partition=(partition_index, partition_count),
    )
