"""Native (C++) event store backend.

The bulk-scan event backend: each app gets an append-only log file managed by
the ``eventlog`` native library (``predictionio_tpu/native/eventlog.cc``) —
fixed numeric record headers scanned with mmap at memory bandwidth, hashed
predicate push-down for entity/event/target/time filters, tombstone deletes.
This plays the role of the reference's HBase backend
(``data/src/main/scala/io/prediction/data/storage/hbase/HBLEvents.scala``,
``HBPEvents.scala``): the native scan is the regionserver-side filter
push-down, the JSON payload decode in Python is the client-side
``Result``→``Event`` codec (``HBEventsUtil.scala:138-273``).

Hash prefilters may (with ~2^-64 probability) pass a colliding record; every
decoded event is re-checked against the exact :class:`EventFilter`, so query
results are always exact.

Durability contract: appends are acknowledged once in the OS page cache and
fdatasync'd on a cadence (every ``_SYNC_EVERY`` appends, after each bulk
``write()`` batch, and on ``close()``) — a power failure can drop the last
few acked single-event inserts, slightly weaker than the SQLite backend's
per-transaction durability (torn tails are truncated on reopen, so the log
stays *consistent* either way). The contract is per file: every writer
segment gets the same cadence, batch sync, and open-time torn-tail
validation as the primary log. Tombstone suppression matches on the
64-bit FNV-1a id hash only: two *distinct* event ids colliding could let a
delete/upsert of one suppress the other during scans, and a primary-log
tombstone whose hash collides with a live id can make ``get()`` miss it
(``get()`` re-verifies the exact id on *matches* and keeps probing other
segments past a colliding record, but a tombstone carries only the hash).
At ~2^-64 per id pair this is accepted; callers needing exactness across
deletes should use the SQLite backend.
"""

from __future__ import annotations

import ctypes
import dataclasses
import json
import mmap
import os
import shutil
import threading
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from ..native import load_library
from .event import Event, to_millis as _ms, validate_event
from .events import EventFilter, EventStore
from .sqlite_events import make_event_id

_INT64_MIN = -(1 << 63)
_INT64_MAX = (1 << 63) - 1

#: fdatasync the log after this many un-synced appends (see module
#: docstring's durability contract).
_SYNC_EVERY = 256


def _lib() -> ctypes.CDLL:
    lib = load_library("eventlog")  # sources come from native.LIBRARIES
    if not getattr(lib, "_pio_configured", False):
        lib.evlog_open.restype = ctypes.c_void_p
        lib.evlog_open.argtypes = [ctypes.c_char_p]
        lib.evlog_close.argtypes = [ctypes.c_void_p]
        lib.evlog_count.restype = ctypes.c_int64
        lib.evlog_count.argtypes = [ctypes.c_void_p]
        lib.evlog_size.restype = ctypes.c_int64
        lib.evlog_size.argtypes = [ctypes.c_void_p]
        lib.evlog_sync.restype = ctypes.c_int
        lib.evlog_sync.argtypes = [ctypes.c_void_p]
        lib.evlog_fnv1a64.restype = ctypes.c_uint64
        lib.evlog_fnv1a64.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.evlog_append.restype = ctypes.c_int64
        lib.evlog_append.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.evlog_scan.restype = ctypes.c_int64
        lib.evlog_scan.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_void_p, ctypes.c_int32,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.evlog_get.restype = ctypes.c_int32
        lib.evlog_get.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.evlog_tombstones.restype = ctypes.c_int64
        lib.evlog_tombstones.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.evlog_ratings_scan.restype = ctypes.c_void_p
        lib.evlog_ratings_scan.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int32, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_void_p, ctypes.c_void_p,
        ]
        for fn in ("evlog_ratings_n_users", "evlog_ratings_n_items",
                   "evlog_ratings_user_pool_bytes",
                   "evlog_ratings_item_pool_bytes"):
            getattr(lib, fn).restype = ctypes.c_int64
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        lib.evlog_ratings_fill.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.evlog_ratings_user_pool_fill.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.evlog_ratings_item_pool_fill.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.evlog_ratings_free.argtypes = [ctypes.c_void_p]
        lib.evlog_append_batch.restype = ctypes.c_int64
        lib.evlog_append_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p,  # time arrays
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,  # hashes
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p,  # payload blob + ends
        ]
        lib._pio_configured = True
    return lib


def _fnv(text: str) -> int:
    data = text.encode("utf-8")
    return int(_lib().evlog_fnv1a64(data, len(data)))


#: primary log filename; writer segments are ``events.w-<id>.log``
_PRIMARY = "events.log"
_SEG_PREFIX = "events.w-"


class NativeScanUnsupported(ValueError):
    """The native fast-path scan declines this workload (unsupported rule
    shape, or writer segments coexisting with primary-log deletes); the
    caller should fall back to the generic — always exact — scan path.
    Distinct from plain ValueError, which signals bad data and must
    propagate."""


def _writer_id_ok(writer_id: str) -> bool:
    return (
        0 < len(writer_id) <= 32
        and all(c.isalnum() or c in "_-" for c in writer_id)
    )


def _merge_rating_parts(parts):
    """Merge per-segment ``scan_ratings`` results: union the id lists in
    segment-major first-appearance order and remap each part's dense
    indices into the union (vectorized per part)."""
    user_ids: list = []
    item_ids: list = []
    u_gidx: dict = {}
    i_gidx: dict = {}
    u_arrays, i_arrays, v_arrays = [], [], []
    for users, items, vals, uids, iids in parts:
        for pool, gidx, out_ids in (
            (uids, u_gidx, user_ids), (iids, i_gidx, item_ids)
        ):
            for k in pool:
                if k not in gidx:
                    gidx[k] = len(out_ids)
                    out_ids.append(k)
        if len(users):
            u_map = np.fromiter(
                (u_gidx[k] for k in uids), dtype=np.int32, count=len(uids)
            )
            i_map = np.fromiter(
                (i_gidx[k] for k in iids), dtype=np.int32, count=len(iids)
            )
            u_arrays.append(u_map[users])
            i_arrays.append(i_map[items])
            v_arrays.append(vals)
    if not u_arrays:
        return (
            np.zeros(0, np.int32), np.zeros(0, np.int32),
            np.zeros(0, np.float32), user_ids, item_ids,
        )
    return (
        np.concatenate(u_arrays), np.concatenate(i_arrays),
        np.concatenate(v_arrays), user_ids, item_ids,
    )


class NativeEventStore(EventStore):
    """Event store over per-app native append-only logs.

    **Multi-writer segments** (the region-parallel-write analogue of the
    reference's HBase path, ``HBPEvents.scala:166-184``): give each ingest
    process its own ``writer_id`` (constructor arg or
    ``PIO_NATIVE_WRITER_ID``) and its fresh-event appends go to a private
    segment file — writers share no lock and no file. Measured (1-core
    dev host, serialization pre-hoisted so the loop is pure
    flock+write(2) — ``ingestbench --contention``): shared-log append
    throughput DROPS as writers are added while segmented appends hold or
    improve; see PERF.md "Ingest lock-contention A/B" for the numbers.
    Full multi-core scaling remains unmeasured here — the claim is
    "removes the shared lock", not a measured linear speedup. Reads
    merge every segment. Correctness of merged
    tombstone filtering rests on a routing invariant: segments receive
    ONLY fresh-id inserts (batch ``write``/``write_new`` paths), while
    explicit-id upserts, deletes, and their tombstones always go to the
    shared primary log — so a tombstone in the primary kills a segment
    record regardless of file order (the id can never be legitimately
    re-inserted into a segment), and order-sensitive delete/re-insert
    sequences are totally ordered within the primary exactly as before.
    """

    def __init__(self, root: str, writer_id: Optional[str] = None):
        self._root = root
        self._lib = _lib()
        if writer_id is None:
            writer_id = os.environ.get("PIO_NATIVE_WRITER_ID") or None
        if writer_id is not None and not _writer_id_ok(writer_id):
            raise ValueError(
                f"writer_id must be 1-32 chars of [A-Za-z0-9_-], "
                f"got {writer_id!r}"
            )
        self._writer_id = writer_id
        #: (app_id, segment filename) -> native handle
        self._handles: Dict[Tuple[int, str], int] = {}
        self._unsynced: Dict[int, int] = {}
        #: app_id -> (primary size at read, tombstone hash array)
        self._tomb_cache: Dict[int, Tuple[int, np.ndarray]] = {}
        self._lock = threading.RLock()
        os.makedirs(root, exist_ok=True)

    def _note_append(self, app_id: int, h: int) -> None:
        """Durability cadence: fdatasync after every ``_SYNC_EVERY``
        appends (the batch paths sync explicitly as well)."""
        with self._lock:
            n = self._unsynced.get(app_id, 0) + 1
            if n >= _SYNC_EVERY:
                self._lib.evlog_sync(h)
                n = 0
            self._unsynced[app_id] = n

    def sync(self, app_id: Optional[int] = None) -> None:
        """fdatasync one app's open logs (or all open logs)."""
        with self._lock:
            for (aid, _fname), h in list(self._handles.items()):
                if app_id is None or aid == app_id:
                    self._lib.evlog_sync(h)
                    self._unsynced[aid] = 0

    def _app_dir(self, app_id: int) -> str:
        return os.path.join(self._root, f"app_{int(app_id)}")

    def _log_path(self, app_id: int, fname: str = _PRIMARY) -> str:
        return os.path.join(self._app_dir(app_id), fname)

    def _segment_files(self, app_id: int) -> list:
        """Existing log files of an app: primary first, then writer
        segments sorted by name (a stable merge order)."""
        try:
            names = os.listdir(self._app_dir(app_id))
        except FileNotFoundError:
            return []
        segs = sorted(
            n for n in names
            if n.startswith(_SEG_PREFIX) and n.endswith(".log")
        )
        return ([_PRIMARY] if _PRIMARY in names else []) + segs

    def _seg_handle(
        self, app_id: int, fname: str, create: bool = False
    ) -> Optional[int]:
        with self._lock:
            key = (app_id, fname)
            h = self._handles.get(key)
            if h:
                return h
            path = self._log_path(app_id, fname)
            if not os.path.exists(path) and not create:
                return None
            os.makedirs(os.path.dirname(path), exist_ok=True)
            h = self._lib.evlog_open(path.encode())
            if not h:
                raise OSError(f"evlog_open failed for {path}")
            self._handles[key] = h
            return h

    def _handle(self, app_id: int, create: bool = False) -> Optional[int]:
        """Primary-log handle (point ops, tombstones, upserts)."""
        return self._seg_handle(app_id, _PRIMARY, create)

    def _writer_handle(self, app_id: int) -> int:
        """Append handle for fresh-event batches: this writer's private
        segment when a writer_id is set, else the shared primary."""
        if self._writer_id is None:
            return self._handle(app_id, create=True)
        return self._seg_handle(
            app_id, f"{_SEG_PREFIX}{self._writer_id}.log", create=True
        )

    def _tombstone_hashes(self, app_id: int) -> np.ndarray:
        """All tombstone id hashes in the primary log (uint64 array).

        Cached per primary-file size: the log is append-only, so an
        unchanged size means an unchanged tombstone set — merged scans
        over a large primary don't pay a second full walk per call."""
        h = self._handle(app_id)
        if h is None:
            return np.zeros(0, dtype=np.uint64)
        try:
            size = os.path.getsize(self._log_path(app_id))
        except OSError:
            size = -1
        with self._lock:
            cached = self._tomb_cache.get(app_id)
            if cached is not None and cached[0] == size and size >= 0:
                return cached[1]
        cap = 1024
        while True:
            out = np.empty(cap, dtype=np.uint64)
            n = self._lib.evlog_tombstones(
                h, out.ctypes.data_as(ctypes.c_void_p), cap
            )
            if n < 0:
                raise OSError(f"evlog_tombstones failed: errno {-n}")
            if n <= cap:
                result = out[:n]
                if size >= 0:
                    with self._lock:
                        self._tomb_cache[app_id] = (size, result)
                return result
            cap = int(n)

    # -- lifecycle --------------------------------------------------------
    def init(self, app_id: int) -> bool:
        self._handle(app_id, create=True)
        return True

    def remove(self, app_id: int) -> bool:
        with self._lock:
            for key in [k for k in self._handles if k[0] == app_id]:
                self._lib.evlog_close(self._handles.pop(key))
            self._tomb_cache.pop(app_id, None)
            shutil.rmtree(self._app_dir(app_id), ignore_errors=True)
        return True

    def close(self) -> None:
        with self._lock:
            for h in self._handles.values():
                self._lib.evlog_sync(h)
                self._lib.evlog_close(h)
            self._handles.clear()
            self._unsynced.clear()

    def write(self, events, app_id: int) -> None:
        """Bulk write; the batch is fdatasync'd once at the end (the
        HBase ``flushCommits`` analogue; the reference's bulk path batches
        via ``saveAsNewAPIHadoopDataset``, ``HBPEvents.scala:166-184``).

        Runs of events WITHOUT explicit ids take the native batch append —
        one lock acquisition + one ``write(2)`` for the whole run
        (``evlog_append_batch``). Events WITH explicit ids need the
        tombstone-first upsert dance and go through :meth:`insert`; runs
        are flushed in input order so append order is preserved exactly.
        """
        try:
            run: list = []
            for e in events:
                if e.event_id is None:
                    run.append(e)
                    continue
                if run:
                    self._write_batch(run, app_id)
                    run = []
                self.insert(e, app_id)
            if run:
                self._write_batch(run, app_id)
        finally:
            # sync even on a mid-batch failure: records appended before the
            # error are acked durably, keeping the docstring's "last few
            # single inserts" durability bound
            self.sync(app_id)

    def write_new(self, events, app_id: int) -> None:
        """Batch append for caller-guaranteed-fresh events: pre-assigned
        ids skip the tombstone-first upsert dance entirely (the batch
        ingestion route's path — ids are minted for the response before
        the write)."""
        events = list(events)
        if events:
            self._write_batch(events, app_id)
        self.sync(app_id)

    def _write_batch(self, events, app_id: int) -> None:
        """Native batch append for fresh inserts (see ``write`` /
        ``write_new``). Uses the event's own id when present (write_new's
        freshness contract), else mints one. Appends go to this writer's
        private segment when a writer_id is set (the multi-writer fast
        path — see class docstring's routing invariant)."""
        self._append_prepared(
            self._writer_handle(app_id), self._prepare_batch(events)
        )

    def _prepare_batch(self, events) -> tuple:
        """Serialize a fresh-insert batch into the C-ready arrays
        ``evlog_append_batch`` takes — all the Python/numpy CPU work,
        separated from the append call so the ingest contention bench can
        measure pure lock+write(2) behavior with serialization hoisted
        out of the timed loop."""
        from .bimap import _fnv1a64_batch

        n = len(events)
        times = np.empty(n, dtype=np.int64)
        ctimes = np.empty(n, dtype=np.int64)
        has_target = np.empty(n, dtype=bool)
        # one batch-hash call for every string of every event (fnv1a64
        # salt=0 == evlog_fnv1a64); layout: per event [etype, entity_key,
        # event, event_id] then per target-bearing event [ttype, target_key]
        strings: list = []
        payloads: list = []
        for i, event in enumerate(events):
            validate_event(event)
            event_id = event.event_id or make_event_id(event)
            # build the payload dict directly instead of
            # dataclasses.replace(event, event_id=...): replace() re-runs
            # __init__/__post_init__ (property re-validation) per event —
            # pure overhead on the bulk path
            d = event.to_json_dict()
            d["eventId"] = event_id
            payloads.append(json.dumps(d).encode("utf-8"))
            times[i] = _ms(event.event_time)
            ctimes[i] = _ms(event.creation_time)
            has_target[i] = event.target_entity_type is not None
            strings += [
                event.entity_type,
                f"{event.entity_type}\x00{event.entity_id}",
                event.event,
                event_id,
            ]
        for event in events:
            if event.target_entity_type is not None:
                strings += [
                    event.target_entity_type,
                    f"{event.target_entity_type}\x00{event.target_entity_id}",
                ]
        hashes = _fnv1a64_batch(strings, salt=0)
        base = hashes[: 4 * n].reshape(n, 4)
        etype_h = np.ascontiguousarray(base[:, 0])
        entity_h = np.ascontiguousarray(base[:, 1])
        event_h = np.ascontiguousarray(base[:, 2])
        id_h = np.ascontiguousarray(base[:, 3])
        ttype_h = np.zeros(n, dtype=np.uint64)
        target_h = np.zeros(n, dtype=np.uint64)
        if has_target.any():
            tpairs = hashes[4 * n:].reshape(-1, 2)
            ttype_h[has_target] = tpairs[:, 0]
            target_h[has_target] = tpairs[:, 1]

        blob = b"".join(payloads)
        ends = np.cumsum([len(p) for p in payloads], dtype=np.int64)
        return (
            n, times, ctimes, etype_h, entity_h, event_h, ttype_h,
            target_h, id_h, blob, ends,
        )

    def _append_prepared(self, h, prepared: tuple) -> None:
        """One native batch append (one flock + one ``write(2)``) of a
        :meth:`_prepare_batch` result."""
        (n, times, ctimes, etype_h, entity_h, event_h, ttype_h, target_h,
         id_h, blob, ends) = prepared
        rc = self._lib.evlog_append_batch(
            h, ctypes.c_int64(n),
            times.ctypes.data_as(ctypes.c_void_p),
            ctimes.ctypes.data_as(ctypes.c_void_p),
            etype_h.ctypes.data_as(ctypes.c_void_p),
            entity_h.ctypes.data_as(ctypes.c_void_p),
            event_h.ctypes.data_as(ctypes.c_void_p),
            ttype_h.ctypes.data_as(ctypes.c_void_p),
            target_h.ctypes.data_as(ctypes.c_void_p),
            id_h.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_char_p(blob),
            ends.ctypes.data_as(ctypes.c_void_p),
        )
        if rc < 0:
            raise OSError(f"evlog_append_batch failed: errno {-rc}")

    # -- point ops --------------------------------------------------------
    def insert(self, event: Event, app_id: int) -> str:
        validate_event(event)
        event_id = event.event_id or make_event_id(event)
        if event.event_id is None:
            # fresh-id insert: eligible for this writer's private segment
            # (the per-event ingest hot path)
            h = self._writer_handle(app_id)
        else:
            # explicit id ⇒ upsert: MUST go to the primary log, where the
            # tombstone and the replacement record are totally ordered
            # (the multi-writer routing invariant)
            h = self._handle(app_id, create=True)
            # Upsert semantics to match the SQLite backend's INSERT OR
            # REPLACE on event_id: a tombstone first kills any earlier record
            # with this id (scans are order-sensitive, so the fresh record
            # appended after it stays live). Harmless no-op for unseen ids.
            tomb = event_id.encode("utf-8")
            toff = self._lib.evlog_append(
                h, 1, _INT64_MIN, 0, 0, 0, 0, 0, 0, _fnv(event_id),
                tomb, len(tomb),
            )
            if toff < 0:
                # an unrecorded tombstone would leave duplicate live records
                raise OSError(f"evlog_append (upsert tombstone) failed: errno {-toff}")
        stored = dataclasses.replace(event, event_id=event_id)
        payload = json.dumps(stored.to_json_dict()).encode("utf-8")
        tt, ti = event.target_entity_type, event.target_entity_id
        off = self._lib.evlog_append(
            h, 0, _ms(event.event_time), _ms(event.creation_time),
            _fnv(event.entity_type),
            _fnv(f"{event.entity_type}\x00{event.entity_id}"),
            _fnv(event.event),
            _fnv(tt) if tt is not None else 0,
            _fnv(f"{tt}\x00{ti}") if tt is not None else 0,
            _fnv(event_id), payload, len(payload),
        )
        if off < 0:
            raise OSError(f"evlog_append failed: errno {-off}")
        self._note_append(app_id, h)
        return event_id

    def get(self, event_id: str, app_id: int) -> Optional[Event]:
        id_hash = _fnv(event_id)
        out_off = ctypes.c_int64()
        out_len = ctypes.c_int64()
        # Primary first: it is authoritative for deletes/upserts. A -1
        # (latest record for the id is a tombstone) means DELETED — do not
        # probe segments, their same-id records are dead by the routing
        # invariant. A hash match whose exact id differs (collision) keeps
        # probing the remaining segments; only the tombstone case is
        # hash-only (the module docstring's accepted ~2^-64 risk).
        for fname in self._segment_files(app_id):
            h = self._seg_handle(app_id, fname)
            if h is None:
                continue
            found = self._lib.evlog_get(
                h, id_hash, ctypes.byref(out_off), ctypes.byref(out_len)
            )
            if found == 1:
                event = self._decode_one(
                    app_id, out_off.value, out_len.value, fname
                )
                # exact-id check guards against id_hash collisions
                if event and event.event_id == event_id:
                    return event
                continue  # colliding foreign id — keep probing
            if found == -1 and fname == _PRIMARY:
                return None  # tombstoned in the authoritative log
        return None

    def delete(self, event_id: str, app_id: int) -> bool:
        if self.get(event_id, app_id) is None:
            return False
        h = self._handle(app_id, create=True)
        payload = event_id.encode("utf-8")
        off = self._lib.evlog_append(
            h, 1, _INT64_MIN, 0, 0, 0, 0, 0, 0, _fnv(event_id),
            payload, len(payload),
        )
        if off >= 0:
            self._note_append(app_id, h)
        return off >= 0

    # -- bulk scan --------------------------------------------------------
    def _scan_offsets(
        self, app_id: int, f: EventFilter
    ) -> Optional[Tuple[list, np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
        """Merged index scan across every segment of the app.

        Returns ``(segment_filenames, seg_idx, offs, lens, times)`` sorted
        by (event_time, segment, offset); ``seg_idx[i]`` indexes into
        ``segment_filenames`` for row i. Secondary-segment matches whose id
        hash appears in the primary's tombstone set are dropped (exact
        under the routing invariant — see class docstring)."""
        segs = self._segment_files(app_id)
        if not segs:
            return None
        tomb = (
            self._tombstone_hashes(app_id)
            if any(s != _PRIMARY for s in segs)
            else np.zeros(0, dtype=np.uint64)
        )
        per_seg = []
        for si, fname in enumerate(segs):
            h = self._seg_handle(app_id, fname)
            if h is None:
                continue
            offs, lens, tms, ids = self._scan_one(h, f)
            if fname != _PRIMARY and len(offs) and len(tomb):
                alive = ~np.isin(ids, tomb)
                offs, lens, tms = offs[alive], lens[alive], tms[alive]
            if len(offs):
                per_seg.append((si, offs, lens, tms))
        if not per_seg:
            return segs, *(np.zeros(0, dtype=np.int64) for _ in range(4))
        if len(per_seg) == 1:
            si, offs, lens, tms = per_seg[0]
            seg_idx = np.full(len(offs), si, dtype=np.int64)
            return segs, seg_idx, offs, lens, tms
        seg_idx = np.concatenate(
            [np.full(len(o), si, dtype=np.int64) for si, o, _, _ in per_seg]
        )
        offs = np.concatenate([o for _, o, _, _ in per_seg])
        lens = np.concatenate([ln for _, _, ln, _ in per_seg])
        tms = np.concatenate([t for _, _, _, t in per_seg])
        order = np.lexsort((offs, seg_idx, tms))  # time, then segment, off
        return segs, seg_idx[order], offs[order], lens[order], tms[order]

    def _scan_one(
        self, h: int, f: EventFilter
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        start = _ms(f.start_time) if f.start_time else _INT64_MIN
        until = _ms(f.until_time) if f.until_time else _INT64_MAX
        etype = _fnv(f.entity_type) if f.entity_type else 0
        entity = (
            _fnv(f"{f.entity_type}\x00{f.entity_id}")
            if f.entity_type and f.entity_id
            else 0
        )
        if f.event_names:
            ev_hashes = np.array(
                [_fnv(n) for n in f.event_names], dtype=np.uint64
            )
            ev_ptr, ev_n = ev_hashes.ctypes.data_as(ctypes.c_void_p), len(ev_hashes)
        else:
            ev_hashes, ev_ptr, ev_n = None, None, 0
        ttype = _fnv(f.target_entity_type) if f.target_entity_type else 0
        target = (
            _fnv(f"{f.target_entity_type}\x00{f.target_entity_id}")
            if f.target_entity_type and f.target_entity_id
            else 0
        )
        has_target = -1
        if f.has_target_entity_type is not None:
            has_target = 1 if f.has_target_entity_type else 0

        # Start with a bounded buffer; the n > cap retry below grows it to
        # the exact match count (one extra scan worst-case) instead of
        # allocating record-count-sized buffers for selective filters.
        cap = min(max(1024, int(self._lib.evlog_count(h))), 65536)
        while True:
            out_off = np.empty(cap, dtype=np.int64)
            out_len = np.empty(cap, dtype=np.int64)
            out_time = np.empty(cap, dtype=np.int64)
            out_id = np.empty(cap, dtype=np.uint64)
            n = self._lib.evlog_scan(
                h, start, until, etype, entity, ev_ptr, ev_n, ttype, target,
                has_target,
                out_off.ctypes.data_as(ctypes.c_void_p),
                out_len.ctypes.data_as(ctypes.c_void_p),
                out_time.ctypes.data_as(ctypes.c_void_p),
                out_id.ctypes.data_as(ctypes.c_void_p), cap,
            )
            if n < 0:
                raise OSError(f"evlog_scan failed: errno {-n}")
            if n <= cap:
                return out_off[:n], out_len[:n], out_time[:n], out_id[:n]
            cap = int(n)

    def _decode_one(
        self, app_id: int, off: int, length: int, fname: str = _PRIMARY
    ) -> Optional[Event]:
        path = self._log_path(app_id, fname)
        with open(path, "rb") as fh:
            fh.seek(off)
            data = fh.read(length)
        try:
            return Event.from_json_dict(json.loads(data))
        except Exception:
            return None

    def find(
        self, app_id: int, filter: Optional[EventFilter] = None
    ) -> Iterator[Event]:
        f = filter or EventFilter()
        scan = self._scan_offsets(app_id, f)
        if scan is None:
            return iter(())
        segs, seg_idx, offs, lens, _times = scan
        return self._decode_iter(app_id, f, segs, seg_idx, offs, lens)

    @staticmethod
    def _dict_matches(f: EventFilter, obj: dict) -> bool:
        """Exact re-check of the string predicates on the raw wire dict —
        the hash-collision guard of :meth:`find` without constructing Event
        objects (time bounds were already applied exactly by the native scan
        on the stored millis)."""
        if f.entity_type is not None and obj.get("entityType") != f.entity_type:
            return False
        if f.entity_id is not None and obj.get("entityId") != f.entity_id:
            return False
        if f.event_names is not None and obj.get("event") not in set(f.event_names):
            return False
        tt = obj.get("targetEntityType")
        if f.has_target_entity_type is not None and (
            f.has_target_entity_type != (tt is not None)
        ):
            return False
        if f.target_entity_type is not None and tt != f.target_entity_type:
            return False
        ti = obj.get("targetEntityId")
        if f.has_target_entity_id is not None and (
            f.has_target_entity_id != (ti is not None)
        ):
            return False
        if f.target_entity_id is not None and ti != f.target_entity_id:
            return False
        return True

    def scan_ratings(self, app_id: int, value_rules: dict):
        """Full DataSource inner loop in C++ (``native/ratings.cc``): one
        pass over the log producing dense index/value arrays plus the
        unique-id lists — per-event Python objects are never created.

        ``value_rules`` maps event name → property name (str) or fixed
        float, with at most one distinct property name across rules (the
        recommendation template needs one). Returns
        ``(users_i32, items_i32, vals_f32, user_ids, item_ids)``. On a
        single log the order is (event_time, offset) — identical index
        assignment to the streaming Python path; with writer segments the
        concatenation is segment-major (index assignment is deterministic
        but segment-ordered — harmless, indices are arbitrary labels).
        Raises ``ValueError`` when the rules need more than one property
        name, or when writer segments coexist with primary-log tombstones
        (the per-segment native scan cannot apply cross-segment deletes);
        callers fall back to the generic path.
        """
        prop_names = {r for r in value_rules.values() if isinstance(r, str)}
        if len(prop_names) > 1:
            raise NativeScanUnsupported(
                f"native ratings scan supports one property name, got "
                f"{sorted(prop_names)}"
            )
        prop_name = next(iter(prop_names), "")
        empty = (
            np.zeros(0, np.int32), np.zeros(0, np.int32),
            np.zeros(0, np.float32), [], [],
        )
        segs = self._segment_files(app_id)
        if not segs:
            return empty
        if segs != [_PRIMARY]:
            if len(self._tombstone_hashes(app_id)):
                raise NativeScanUnsupported(
                    "native ratings scan cannot apply primary-log deletes "
                    "across writer segments; use the generic scan path"
                )
            parts = []
            for fname in segs:
                h = self._seg_handle(app_id, fname)
                if h is not None:
                    parts.append(self._scan_ratings_one(h, value_rules, prop_name))
            return _merge_rating_parts(parts) if parts else empty
        h = self._handle(app_id)
        if h is None:
            return empty
        return self._scan_ratings_one(h, value_rules, prop_name)

    def _scan_ratings_one(self, h: int, value_rules: dict, prop_name: str):
        names = list(value_rules)
        n = len(names)
        hashes = np.asarray([_fnv(nm) for nm in names], dtype=np.uint64)
        is_prop = np.asarray(
            [1 if isinstance(value_rules[nm], str) else 0 for nm in names],
            dtype=np.int32,
        )
        fixed = np.asarray(
            [
                0.0 if isinstance(value_rules[nm], str) else float(value_rules[nm])
                for nm in names
            ],
            dtype=np.float64,
        )
        names_buf = b"".join(nm.encode("utf-8") + b"\0" for nm in names)
        out_n = ctypes.c_int64(0)
        out_bad = ctypes.c_int64(0)
        res = self._lib.evlog_ratings_scan(
            h,
            hashes.ctypes.data_as(ctypes.c_void_p),
            is_prop.ctypes.data_as(ctypes.c_void_p),
            fixed.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int32(n),
            names_buf,
            prop_name.encode("utf-8"),
            ctypes.byref(out_n),
            ctypes.byref(out_bad),
        )
        if not res:
            raise OSError("evlog_ratings_scan failed (mmap)")
        try:
            if out_bad.value:
                raise ValueError(
                    f"{out_bad.value} events missing required property "
                    f"{prop_name!r} (or malformed payloads)"
                )
            count = out_n.value
            users = np.empty(count, dtype=np.int32)
            items = np.empty(count, dtype=np.int32)
            vals = np.empty(count, dtype=np.float32)
            if count:
                self._lib.evlog_ratings_fill(
                    res,
                    users.ctypes.data_as(ctypes.c_void_p),
                    items.ctypes.data_as(ctypes.c_void_p),
                    vals.ctypes.data_as(ctypes.c_void_p),
                )

            def pool(n_fn, bytes_fn, fill_fn):
                n_ids = n_fn(res)
                nbytes = bytes_fn(res)
                buf = np.empty(nbytes, dtype=np.uint8)
                ends = np.empty(n_ids, dtype=np.int64)
                if n_ids:
                    fill_fn(
                        res,
                        buf.ctypes.data_as(ctypes.c_void_p),
                        ends.ctypes.data_as(ctypes.c_void_p),
                    )
                raw = buf.tobytes()
                out, start = [], 0
                for end in ends.tolist():
                    out.append(raw[start:end].decode("utf-8"))
                    start = end
                return out

            user_ids = pool(
                self._lib.evlog_ratings_n_users,
                self._lib.evlog_ratings_user_pool_bytes,
                self._lib.evlog_ratings_user_pool_fill,
            )
            item_ids = pool(
                self._lib.evlog_ratings_n_items,
                self._lib.evlog_ratings_item_pool_bytes,
                self._lib.evlog_ratings_item_pool_fill,
            )
            return users, items, vals, user_ids, item_ids
        finally:
            self._lib.evlog_ratings_free(res)

    @staticmethod
    def _empty_cols() -> dict:
        return {
            "event": [], "entity_type": [], "entity_id": [],
            "target_entity_type": [], "target_entity_id": [],
            "properties": [], "event_time_ms": np.asarray([], dtype=np.int64),
        }

    def scan_columnar(self, app_id: int, filter: Optional[EventFilter] = None):
        """Bulk scan returning a column dict (training-path fast lane; same
        contract as :meth:`SqliteEventStore.scan_columnar`). Payloads are
        decoded straight from the mmap'd log into columns — no per-event
        ``Event``/``DataMap`` objects."""
        chunks = list(self.scan_columnar_iter(app_id, filter))
        if not chunks:
            return self._empty_cols()
        if len(chunks) == 1:
            return chunks[0]
        out = {
            k: [v for c in chunks for v in c[k]]
            for k in chunks[0]
            if k != "event_time_ms"
        }
        out["event_time_ms"] = np.concatenate(
            [c["event_time_ms"] for c in chunks]
        )
        return out

    def scan_columnar_iter(
        self,
        app_id: int,
        filter: Optional[EventFilter] = None,
        chunk_rows: int = 1_000_000,
    ):
        """Chunked columnar scan (``EventStore.scan_columnar_iter`` fast
        path): the native index scan resolves all offsets up front (numpy
        arrays, 20 B/event), then payload decode proceeds chunk by chunk
        from the mmap — bounded Python-object footprint regardless of app
        size (the region-split analogue, ``HBPEvents.scala:91-97``)."""
        f = filter or EventFilter()
        scan = self._scan_offsets(app_id, f)
        if scan is None:
            return
        segs, seg_idx, offs, lens, tms = scan
        if f.reversed:
            seg_idx, offs, lens, tms = (
                seg_idx[::-1], offs[::-1], lens[::-1], tms[::-1]
            )
        limit = f.limit if f.limit is not None and f.limit >= 0 else None
        if not len(offs):
            return
        emitted = 0
        with self._segment_mmaps(self, app_id, segs) as mms:
            cols = self._empty_cols()
            times: list = []
            for si, off, length, tm in zip(
                seg_idx.tolist(), offs.tolist(), lens.tolist(), tms.tolist()
            ):
                mm = mms[si]
                obj = json.loads(mm[off : off + length])
                if not self._dict_matches(f, obj):
                    continue
                cols["event"].append(obj["event"])
                cols["entity_type"].append(obj["entityType"])
                cols["entity_id"].append(obj["entityId"])
                cols["target_entity_type"].append(obj.get("targetEntityType"))
                cols["target_entity_id"].append(obj.get("targetEntityId"))
                cols["properties"].append(obj.get("properties") or {})
                times.append(tm)
                emitted += 1
                full = len(times) >= chunk_rows
                done = limit is not None and emitted >= limit
                if full or done:
                    cols["event_time_ms"] = np.asarray(times, dtype=np.int64)
                    yield cols
                    if done:
                        return
                    cols = self._empty_cols()
                    times = []
            if times:
                cols["event_time_ms"] = np.asarray(times, dtype=np.int64)
                yield cols

    class _segment_mmaps:
        """Context manager mapping segment index → read mmap, opened
        lazily (a scan may touch only some segments)."""

        def __init__(self, store, app_id: int, segs: list):
            self._store, self._app_id, self._segs = store, app_id, segs
            self._files: list = []
            self._mms: dict = {}

        def __enter__(self):
            return self

        def __getitem__(self, si: int):
            mm = self._mms.get(si)
            if mm is None:
                path = self._store._log_path(self._app_id, self._segs[si])
                fh = open(path, "rb")
                self._files.append(fh)
                size = os.fstat(fh.fileno()).st_size
                mm = mmap.mmap(fh.fileno(), size, access=mmap.ACCESS_READ)
                self._mms[si] = mm
            return mm

        def __exit__(self, *exc):
            for mm in self._mms.values():
                try:
                    mm.close()
                except Exception:
                    pass
            for fh in self._files:
                try:
                    fh.close()
                except Exception:
                    pass

    def _decode_iter(
        self, app_id: int, f: EventFilter, segs: list,
        seg_idx: np.ndarray, offs: np.ndarray, lens: np.ndarray,
    ) -> Iterator[Event]:
        if f.reversed:
            seg_idx, offs, lens = seg_idx[::-1], offs[::-1], lens[::-1]
        limit = f.limit if f.limit is not None and f.limit >= 0 else None
        emitted = 0
        if len(offs) == 0:
            return
        with self._segment_mmaps(self, app_id, segs) as mms:
            for si, off, length in zip(
                seg_idx.tolist(), offs.tolist(), lens.tolist()
            ):
                obj = json.loads(mms[si][off : off + length])
                event = Event.from_json_dict(obj)
                # exact re-check (hash-collision guard)
                if not f.matches(event):
                    continue
                yield event
                emitted += 1
                if limit is not None and emitted >= limit:
                    return
