"""Schema-free property bags.

TPU-native rebuild of the reference's ``DataMap`` / ``PropertyMap``
(``data/src/main/scala/io/prediction/data/storage/DataMap.scala:38-194`` and
``PropertyMap.scala``): an immutable string-keyed bag of JSON values with typed
accessors, plus a ``PropertyMap`` that carries first/last-updated times from
property aggregation.

The reference backs this with json4s ``JValue``; here values are plain Python
JSON-compatible objects (``None``/bool/int/float/str/list/dict).
"""

from __future__ import annotations

import datetime as _dt
from typing import Any, Iterator, Mapping, Optional, Type, TypeVar

T = TypeVar("T")

_JSON_TYPES = (type(None), bool, int, float, str, list, dict)


class DataMapException(Exception):
    """Raised on missing required fields or type mismatches.

    Mirrors ``DataMapException`` in ``DataMap.scala:30-36``.
    """


def _check_json_value(key: str, value: Any) -> Any:
    if not isinstance(value, _JSON_TYPES):
        raise DataMapException(
            f"DataMap field {key!r} holds non-JSON value of type "
            f"{type(value).__name__}"
        )
    return value


class DataMap(Mapping[str, Any]):
    """Immutable mapping of field name → JSON value with typed ``get``.

    Reference semantics (``DataMap.scala``):

    - ``get_as(name, as_type)`` raises :class:`DataMapException` when the
      field is missing (``require`` behavior, ``DataMap.scala:49-55``).
    - ``get_opt`` returns ``None`` when missing.
    - ``get(name, default)`` keeps the standard ``Mapping.get`` contract.
    - ``++`` merge (here ``|`` / :meth:`merge`) is right-biased.
    - ``--`` removal (:meth:`without`).
    """

    __slots__ = ("_fields",)

    def __init__(self, fields: Optional[Mapping[str, Any]] = None):
        data = dict(fields or {})
        for k, v in data.items():
            if not isinstance(k, str):
                raise DataMapException(f"DataMap keys must be str, got {k!r}")
            _check_json_value(k, v)
        object.__setattr__(self, "_fields", data)

    # -- Mapping protocol -------------------------------------------------
    def __getitem__(self, key: str) -> Any:
        return self._fields[key]

    def __iter__(self) -> Iterator[str]:
        return iter(self._fields)

    def __len__(self) -> int:
        return len(self._fields)

    def __contains__(self, key: object) -> bool:
        return key in self._fields

    # -- Typed accessors ---------------------------------------------------
    def require(self, name: str) -> None:
        if name not in self._fields:
            raise DataMapException(f"The field {name} is required.")

    def get(self, name: str, default: Any = None) -> Any:
        """Standard ``Mapping.get``: value or ``default`` when missing."""
        return self._fields.get(name, default)

    def get_as(self, name: str, as_type: Type[T] = object) -> T:
        """Return field ``name`` coerced to ``as_type``; raise if missing
        (the reference's typed ``get[T]``)."""
        self.require(name)
        return self._coerce(name, self._fields[name], as_type)

    def get_opt(self, name: str, as_type: Type[T] = object) -> Optional[T]:
        if name not in self._fields:
            return None
        return self._coerce(name, self._fields[name], as_type)

    def get_or_else(self, name: str, default: T) -> T:
        """Typed get with fallback (``DataMap.scala`` ``getOrElse``)."""
        value = self.get_opt(name, type(default))
        return default if value is None else value

    @staticmethod
    def _coerce(name: str, value: Any, as_type: Type[T]) -> T:
        if as_type is object or isinstance(value, as_type):
            return value  # type: ignore[return-value]
        # Numeric widening: int stored where float requested.
        if as_type is float and isinstance(value, int) and not isinstance(value, bool):
            return float(value)  # type: ignore[return-value]
        raise DataMapException(
            f"The field {name} has type {type(value).__name__}; "
            f"expected {as_type.__name__}."
        )

    # -- Combinators -------------------------------------------------------
    def merge(self, other: "DataMap") -> "DataMap":
        """Right-biased merge (reference ``++``, ``DataMap.scala:139-141``)."""
        merged = dict(self._fields)
        merged.update(other._fields)
        return DataMap(merged)

    __or__ = merge

    def without(self, keys) -> "DataMap":
        """Remove ``keys`` (reference ``--``, ``DataMap.scala:143-146``)."""
        drop = set(keys)
        return DataMap({k: v for k, v in self._fields.items() if k not in drop})

    def is_empty(self) -> bool:
        return not self._fields

    def keyset(self) -> set:
        return set(self._fields)

    def to_dict(self) -> dict:
        return dict(self._fields)

    def __eq__(self, other: object) -> bool:
        if isinstance(other, DataMap):
            return self._fields == other._fields
        if isinstance(other, Mapping):
            return self._fields == dict(other)
        return NotImplemented

    def __hash__(self) -> int:
        import json

        # Canonical JSON so equal maps (incl. nested dicts in any insertion
        # order) hash equally.
        return hash(json.dumps(self._fields, sort_keys=True, default=repr))

    def __repr__(self) -> str:
        return f"DataMap({self._fields!r})"


class PropertyMap(DataMap):
    """A :class:`DataMap` plus aggregation provenance.

    Produced by property aggregation over ``$set/$unset/$delete`` events
    (reference ``PropertyMap.scala``): ``first_updated`` / ``last_updated``
    are event times of the earliest / latest contributing events.
    """

    __slots__ = ("first_updated", "last_updated")

    def __init__(
        self,
        fields: Optional[Mapping[str, Any]],
        first_updated: _dt.datetime,
        last_updated: _dt.datetime,
    ):
        super().__init__(fields)
        object.__setattr__(self, "first_updated", first_updated)
        object.__setattr__(self, "last_updated", last_updated)

    def __repr__(self) -> str:
        return (
            f"PropertyMap({self.to_dict()!r}, first_updated={self.first_updated}, "
            f"last_updated={self.last_updated})"
        )

    def __eq__(self, other: object) -> bool:
        if isinstance(other, PropertyMap):
            return (
                self.to_dict() == other.to_dict()
                and self.first_updated == other.first_updated
                and self.last_updated == other.last_updated
            )
        return super().__eq__(other)

    def __hash__(self) -> int:
        return hash((super().__hash__(), self.first_updated, self.last_updated))
