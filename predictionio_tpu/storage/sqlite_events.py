"""SQLite-backed event store.

The local backend for :class:`~predictionio_tpu.storage.events.EventStore`,
playing the role of the reference's HBase events backend
(``data/src/main/scala/io/prediction/data/storage/hbase/HBLEvents.scala`` /
``HBPEvents.scala``): one table per app (``events_<appId>``, the analogue of
the HBase table-per-app layout in ``HBEventsUtil.scala:54-66``), an event-time
index for range scans (the analogue of the scan builder's time-range push-down,
``HBEventsUtil.scala:280-404``), and composite event ids that embed the entity
hash, event-time millis, and a uuid — the reference's row-key scheme
(``HBEventsUtil.scala:75-123``) kept as an *id format* rather than a physical
sort order.

A bulk columnar scan path (:meth:`SqliteEventStore.scan_columnar`) returns
numpy arrays directly, feeding the training pipeline without per-event Python
object overhead — the TPU-infeed analogue of ``newAPIHadoopRDD`` region scans
(``HBPEvents.scala:58-98``).
"""

from __future__ import annotations

import datetime as _dt
import hashlib
import json
import os
import sqlite3
import threading
from typing import Iterator, Optional, Sequence

from .data_map import DataMap
from .event import UTC, Event, to_millis as _ms, validate_event
from .events import EventFilter, EventStore

_SCHEMA = """
CREATE TABLE IF NOT EXISTS "{table}" (
  event_id TEXT PRIMARY KEY,
  event TEXT NOT NULL,
  entity_type TEXT NOT NULL,
  entity_id TEXT NOT NULL,
  target_entity_type TEXT,
  target_entity_id TEXT,
  properties TEXT NOT NULL,
  event_time_ms INTEGER NOT NULL,
  event_time_offset_s INTEGER NOT NULL DEFAULT 0,
  tags TEXT NOT NULL DEFAULT '[]',
  pr_id TEXT,
  creation_time_ms INTEGER NOT NULL
);
CREATE INDEX IF NOT EXISTS "idx_{table}_time" ON "{table}" (event_time_ms);
CREATE INDEX IF NOT EXISTS "idx_{table}_entity"
  ON "{table}" (entity_type, entity_id, event_time_ms);
"""


def _from_ms(ms: int, offset_s: int) -> _dt.datetime:
    tz = _dt.timezone(_dt.timedelta(seconds=offset_s)) if offset_s else UTC
    return _dt.datetime.fromtimestamp(ms / 1000.0, tz=tz)


def make_event_id(event: Event) -> str:
    """Composite id: md5(entityType-entityId)[:16] ∥ millis ∥ uuid-low.

    Same information content as the reference row key
    (``HBEventsUtil.scala:90-102``): dedup by (entity, time, uniquifier) and
    self-describing enough to locate the owning entity from the id alone.
    """
    md5 = hashlib.md5(
        f"{event.entity_type}-{event.entity_id}".encode()
    ).hexdigest()[:16]
    millis = _ms(event.event_time) & 0xFFFFFFFFFFFFFFFF
    # raw urandom instead of uuid4: same 64 bits of uniquifier entropy
    # without UUID-object construction (bulk-ingest hot path)
    uuid_low = int.from_bytes(os.urandom(8), "big")
    return f"{md5}{millis:016x}{uuid_low:016x}"


class SqliteEventStore(EventStore):
    """Event store over a single SQLite database file (or ``:memory:``)."""

    def __init__(self, path: str = ":memory:", namespace: str = "pio_event"):
        self._path = path
        self._namespace = namespace
        self._lock = threading.RLock()
        if path != ":memory:":
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")

    def _table(self, app_id: int) -> str:
        # Analogue of "<namespace>:events_<appId>" (HBEventsUtil.scala:54).
        return f"{self._namespace}_events_{int(app_id)}"

    def _ensure_table(self, app_id: int) -> str:
        table = self._table(app_id)
        with self._lock:
            self._conn.executescript(_SCHEMA.format(table=table))
        return table

    # -- lifecycle --------------------------------------------------------
    def init(self, app_id: int) -> bool:
        self._ensure_table(app_id)
        return True

    def remove(self, app_id: int) -> bool:
        table = self._table(app_id)
        with self._lock:
            self._conn.execute(f'DROP TABLE IF EXISTS "{table}"')
            self._conn.commit()
        return True

    def close(self) -> None:
        with self._lock:
            self._conn.close()

    # -- point ops --------------------------------------------------------
    @staticmethod
    def _event_row(event: Event, event_id: str) -> tuple:
        offset = event.event_time.utcoffset() or _dt.timedelta(0)
        return (
            event_id,
            event.event,
            event.entity_type,
            event.entity_id,
            event.target_entity_type,
            event.target_entity_id,
            json.dumps(event.properties.to_dict()),
            _ms(event.event_time),
            int(offset.total_seconds()),
            json.dumps(list(event.tags)),
            event.pr_id,
            _ms(event.creation_time),
        )

    def insert(self, event: Event, app_id: int) -> str:
        validate_event(event)
        table = self._ensure_table(app_id)
        event_id = event.event_id or make_event_id(event)
        with self._lock:
            self._conn.execute(
                f'INSERT OR REPLACE INTO "{table}" VALUES (?,?,?,?,?,?,?,?,?,?,?,?)',
                self._event_row(event, event_id),
            )
            self._conn.commit()
        return event_id

    def write(self, events: Sequence[Event], app_id: int) -> None:
        """Bulk load in one transaction (the ``PEvents.write`` fast path)."""
        table = self._ensure_table(app_id)
        rows = []
        for e in events:
            validate_event(e)
            rows.append(self._event_row(e, e.event_id or make_event_id(e)))
        with self._lock:
            self._conn.executemany(
                f'INSERT OR REPLACE INTO "{table}" VALUES (?,?,?,?,?,?,?,?,?,?,?,?)',
                rows,
            )
            self._conn.commit()

    def _row_to_event(self, row) -> Event:
        return Event(
            event_id=row[0],
            event=row[1],
            entity_type=row[2],
            entity_id=row[3],
            target_entity_type=row[4],
            target_entity_id=row[5],
            properties=DataMap(json.loads(row[6])),
            event_time=_from_ms(row[7], row[8]),
            tags=tuple(json.loads(row[9])),
            pr_id=row[10],
            creation_time=_from_ms(row[11], 0),
        )

    def get(self, event_id: str, app_id: int) -> Optional[Event]:
        table = self._ensure_table(app_id)
        with self._lock:
            cur = self._conn.execute(
                f'SELECT * FROM "{table}" WHERE event_id = ?', (event_id,)
            )
            row = cur.fetchone()
        return self._row_to_event(row) if row else None

    def delete(self, event_id: str, app_id: int) -> bool:
        table = self._ensure_table(app_id)
        with self._lock:
            cur = self._conn.execute(
                f'DELETE FROM "{table}" WHERE event_id = ?', (event_id,)
            )
            self._conn.commit()
            return cur.rowcount > 0

    # -- bulk scan --------------------------------------------------------
    def _build_query(self, table: str, f: EventFilter, columns: str = "*"):
        clauses, params = [], []
        if f.start_time is not None:
            clauses.append("event_time_ms >= ?")
            params.append(_ms(f.start_time))
        if f.until_time is not None:
            clauses.append("event_time_ms < ?")
            params.append(_ms(f.until_time))
        if f.entity_type is not None:
            clauses.append("entity_type = ?")
            params.append(f.entity_type)
        if f.entity_id is not None:
            clauses.append("entity_id = ?")
            params.append(f.entity_id)
        if f.event_names is not None:
            marks = ",".join("?" * len(f.event_names))
            clauses.append(f"event IN ({marks})")
            params.extend(f.event_names)
        if f.has_target_entity_type is True:
            clauses.append("target_entity_type IS NOT NULL")
        if f.has_target_entity_type is False:
            clauses.append("target_entity_type IS NULL")
        if f.target_entity_type is not None:
            clauses.append("target_entity_type = ?")
            params.append(f.target_entity_type)
        if f.has_target_entity_id is True:
            clauses.append("target_entity_id IS NOT NULL")
        if f.has_target_entity_id is False:
            clauses.append("target_entity_id IS NULL")
        if f.target_entity_id is not None:
            clauses.append("target_entity_id = ?")
            params.append(f.target_entity_id)
        where = f"WHERE {' AND '.join(clauses)}" if clauses else ""
        order = "DESC" if f.reversed else "ASC"
        sql = (
            f'SELECT {columns} FROM "{table}" {where} '
            f"ORDER BY event_time_ms {order}, event_id {order}"
        )
        if f.limit is not None and f.limit >= 0:
            sql += " LIMIT ?"
            params.append(f.limit)
        return sql, params

    def find(
        self, app_id: int, filter: Optional[EventFilter] = None
    ) -> Iterator[Event]:
        table = self._ensure_table(app_id)
        f = filter or EventFilter()
        sql, params = self._build_query(table, f)

        def stream() -> Iterator[Event]:
            # Stream in batches so million-event scans never materialize the
            # whole table; the lock is held only per batch.
            with self._lock:
                cursor = self._conn.execute(sql, params)
            while True:
                with self._lock:
                    rows = cursor.fetchmany(1000)
                if not rows:
                    return
                for r in rows:
                    yield self._row_to_event(r)

        return stream()

    def scan_columnar(self, app_id: int, filter: Optional[EventFilter] = None):
        """Bulk scan returning column dict of python lists / numpy arrays.

        The training-path fast lane: entity ids, target ids, event names and a
        float property column are materialized without building per-event
        objects, ready for BiMap indexing + device infeed.
        """
        import numpy as np

        table = self._ensure_table(app_id)
        f = filter or EventFilter()
        sql, params = self._build_query(
            table,
            f,
            columns="event, entity_type, entity_id, target_entity_type, "
            "target_entity_id, properties, event_time_ms",
        )
        with self._lock:
            rows = self._conn.execute(sql, params).fetchall()
        return self._rows_to_cols(rows)

    @staticmethod
    def _rows_to_cols(rows) -> dict:
        import numpy as np

        return {
            "event": [r[0] for r in rows],
            "entity_type": [r[1] for r in rows],
            "entity_id": [r[2] for r in rows],
            "target_entity_type": [r[3] for r in rows],
            "target_entity_id": [r[4] for r in rows],
            "properties": [json.loads(r[5]) for r in rows],
            "event_time_ms": np.asarray([r[6] for r in rows], dtype=np.int64),
        }

    def scan_columnar_iter(
        self,
        app_id: int,
        filter: Optional[EventFilter] = None,
        chunk_rows: int = 1_000_000,
    ):
        """Chunked columnar scan (``EventStore.scan_columnar_iter`` fast
        path): one cursor, ``fetchmany`` batches, no per-event objects."""
        table = self._ensure_table(app_id)
        f = filter or EventFilter()
        sql, params = self._build_query(
            table,
            f,
            columns="event, entity_type, entity_id, target_entity_type, "
            "target_entity_id, properties, event_time_ms",
        )
        with self._lock:
            cursor = self._conn.execute(sql, params)
        while True:
            with self._lock:
                rows = cursor.fetchmany(chunk_rows)
            if not rows:
                return
            yield self._rows_to_cols(rows)
