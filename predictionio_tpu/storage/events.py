"""Event store interface.

Rebuild of the reference's event DAO traits
(``data/src/main/scala/io/prediction/data/storage/LEvents.scala:30-402`` and
``PEvents.scala:30-119``). The L/P split (local futures vs. Spark RDDs)
collapses here into one interface: point ops for the serving path and bulk
``find``/``aggregate_properties`` scans for the training path. Backends return
plain iterators; the training pipeline turns them into device-ready arrays
(the TPU analogue of ``newAPIHadoopRDD`` feeding executors).
"""

from __future__ import annotations

import abc
import dataclasses
import datetime as _dt
from typing import Dict, Iterator, Optional, Sequence

from .aggregator import AGGREGATOR_EVENT_NAMES, aggregate_properties, aggregate_single
from .data_map import PropertyMap
from .event import UTC, Event


@dataclasses.dataclass(frozen=True)
class EventFilter:
    """Bulk-scan predicate set, mirroring the parameters of
    ``LEvents.futureFind`` (``LEvents.scala:121-147``) / ``PEvents.find``
    (``PEvents.scala:45-73``).

    To select events *without* a target entity, use
    ``has_target_entity_type=False`` (the analogue of the reference's
    ``targetEntityType = Some(None)`` encoding).
    """

    start_time: Optional[_dt.datetime] = None  # inclusive
    until_time: Optional[_dt.datetime] = None  # exclusive
    entity_type: Optional[str] = None
    entity_id: Optional[str] = None
    event_names: Optional[Sequence[str]] = None
    target_entity_type: Optional[str] = None
    target_entity_id: Optional[str] = None
    has_target_entity_type: Optional[bool] = None  # None = don't care
    has_target_entity_id: Optional[bool] = None
    limit: Optional[int] = None  # None or <0 = unlimited (LEvents.scala:137)
    reversed: bool = False  # descending event time (LEvents.scala:139)

    def __post_init__(self):
        # Naive bounds are taken as UTC, matching Event's convention.
        for field in ("start_time", "until_time"):
            t = getattr(self, field)
            if t is not None and t.tzinfo is None:
                object.__setattr__(self, field, t.replace(tzinfo=UTC))

    def matches(self, e: Event) -> bool:
        if self.start_time is not None and e.event_time < self.start_time:
            return False
        if self.until_time is not None and e.event_time >= self.until_time:
            return False
        if self.entity_type is not None and e.entity_type != self.entity_type:
            return False
        if self.entity_id is not None and e.entity_id != self.entity_id:
            return False
        if self.event_names is not None and e.event not in set(self.event_names):
            return False
        if self.has_target_entity_type is not None:
            if self.has_target_entity_type != (e.target_entity_type is not None):
                return False
        if (
            self.target_entity_type is not None
            and e.target_entity_type != self.target_entity_type
        ):
            return False
        if self.has_target_entity_id is not None:
            if self.has_target_entity_id != (e.target_entity_id is not None):
                return False
        if (
            self.target_entity_id is not None
            and e.target_entity_id != self.target_entity_id
        ):
            return False
        return True


class EventStore(abc.ABC):
    """Unified event DAO (reference ``LEvents`` + ``PEvents``)."""

    # -- lifecycle (LEvents.scala:44-56) ----------------------------------
    @abc.abstractmethod
    def init(self, app_id: int) -> bool:
        """Initialize per-app storage (HBase table creation analogue)."""

    @abc.abstractmethod
    def remove(self, app_id: int) -> bool:
        """Remove all events of an app and its storage."""

    def close(self) -> None:
        """Release resources (``LEvents.scala:63``)."""

    # -- point ops (LEvents.scala:65-119) ---------------------------------
    @abc.abstractmethod
    def insert(self, event: Event, app_id: int) -> str:
        """Insert one event, returning its assigned event id."""

    @abc.abstractmethod
    def get(self, event_id: str, app_id: int) -> Optional[Event]:
        ...

    @abc.abstractmethod
    def delete(self, event_id: str, app_id: int) -> bool:
        ...

    # -- bulk scan (LEvents.scala:121-145 / PEvents.scala:45-73) ----------
    @abc.abstractmethod
    def find(
        self, app_id: int, filter: Optional[EventFilter] = None
    ) -> Iterator[Event]:
        """Events ordered by event time (descending when ``filter.reversed``)."""

    def scan_columnar_iter(
        self,
        app_id: int,
        filter: Optional[EventFilter] = None,
        chunk_rows: int = 1_000_000,
    ) -> Iterator[dict]:
        """Chunked columnar scan: yields column dicts of at most
        ``chunk_rows`` rows each (same keys as ``scan_columnar``).

        The streaming-infeed primitive (the analogue of the reference's
        region-split reads feeding executors, ``HBPEvents.scala:58-98``):
        a training pipeline can translate + stage each chunk while the next
        is being read, holding one chunk of Python objects at a time
        instead of the whole app. Backends override with columnar fast
        paths; this base version derives chunks from ``find``.
        """
        import numpy as np

        from .event import to_millis

        def new_cols() -> dict:
            return {
                "event": [], "entity_type": [], "entity_id": [],
                "target_entity_type": [], "target_entity_id": [],
                "properties": [], "event_time_ms": [],
            }

        cols = new_cols()
        for e in self.find(app_id, filter):
            cols["event"].append(e.event)
            cols["entity_type"].append(e.entity_type)
            cols["entity_id"].append(e.entity_id)
            cols["target_entity_type"].append(e.target_entity_type)
            cols["target_entity_id"].append(e.target_entity_id)
            cols["properties"].append(e.properties.to_dict())
            cols["event_time_ms"].append(to_millis(e.event_time))
            if len(cols["event"]) >= chunk_rows:
                cols["event_time_ms"] = np.asarray(
                    cols["event_time_ms"], dtype=np.int64
                )
                yield cols
                cols = new_cols()
        if cols["event"]:
            cols["event_time_ms"] = np.asarray(
                cols["event_time_ms"], dtype=np.int64
            )
            yield cols

    # -- derived views ----------------------------------------------------
    def aggregate_properties(
        self,
        app_id: int,
        entity_type: str,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[Sequence[str]] = None,
    ) -> Dict[str, PropertyMap]:
        """Entity-state view over special events
        (``LEvents.scala:147-195`` / ``PEvents.scala:75-103``)."""
        events = self.find(
            app_id,
            EventFilter(
                start_time=start_time,
                until_time=until_time,
                entity_type=entity_type,
                event_names=AGGREGATOR_EVENT_NAMES,
            ),
        )
        result = aggregate_properties(events)
        if required:
            req = set(required)
            result = {
                k: v for k, v in result.items() if req.issubset(v.keyset())
            }
        return result

    def aggregate_properties_single(
        self,
        app_id: int,
        entity_type: str,
        entity_id: str,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
    ) -> Optional[PropertyMap]:
        """One entity's state (``LEvents.scala:197-245``)."""
        events = self.find(
            app_id,
            EventFilter(
                start_time=start_time,
                until_time=until_time,
                entity_type=entity_type,
                entity_id=entity_id,
                event_names=AGGREGATOR_EVENT_NAMES,
            ),
        )
        return aggregate_single(events)

    def find_single_entity(
        self,
        app_id: int,
        entity_type: str,
        entity_id: str,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        limit: Optional[int] = None,
        latest: bool = True,
    ) -> Iterator[Event]:
        """Serving-side low-latency read for one entity
        (``LEvents.scala:306-402``) — used by e-commerce-style engines to
        apply live constraints at query time."""
        return self.find(
            app_id,
            EventFilter(
                entity_type=entity_type,
                entity_id=entity_id,
                event_names=event_names,
                target_entity_type=target_entity_type,
                target_entity_id=target_entity_id,
                limit=limit,
                reversed=latest,
            ),
        )

    def write(self, events: Sequence[Event], app_id: int) -> None:
        """Bulk write (``PEvents.write``, ``PEvents.scala:105-118``)."""
        for e in events:
            self.insert(e, app_id)

    def write_new(self, events: Sequence[Event], app_id: int) -> None:
        """Bulk write of events the caller GUARANTEES are fresh (every
        ``event_id`` newly minted and unique) — backends may skip their
        upsert/replace machinery. Default: plain ``write``."""
        self.write(events, app_id)
