"""Live partition migration: dual-write + backfill + cutover watermark.

PR 13 froze the event keyspace at boot: changing the partition count
was an export/import outage (the old ``docs/storage.md`` failure-mode
row said so out loud). This module makes resharding an *online*
operation (``docs/storage.md#live-migration``): the old ``N``-partition
layout and the new ``M``-partition layout run concurrently, and a
:class:`PartitionMigration` coordinator walks four phases:

``dual_write``
    Every acked event write lands on the old layout (the ack — clients
    see exactly the pre-migration durability contract) and is mirrored
    to the new layout **asynchronously** through a durable
    :class:`PendingQueue`: append is fsync'd before the writer returns,
    drain happens on the coordinator's cadence, so a new-layout primary
    hiccup can never block or fail ingest.

``backfill``
    A worker streams each old partition's **oplog history** into the
    new layout with a durable per-partition progress cursor. Replaying
    the old feed (not a table scan) is what makes the copy convergent:
    logged event ops are *resolved* (final event ids) and idempotent
    (upsert/delete), and the old oplog is a total order per partition —
    so however mirror writes and backfill interleave, once the cursor
    reaches the head the new layout equals the old layout's state.
    Crash anywhere, restart, re-apply from the cursor: same state.

``ready`` → ``cutover``
    The **watermark** verifies per keyspace slice (every old partition:
    backfill cursor == feed head) and that the mirror queue is drained.
    :meth:`PartitionMigration.cutover` then freezes writes (the event
    server answers 503 + ``Retry-After`` — the one bounded unavailable
    window, docs/storage.md#live-migration), re-drains, re-verifies,
    and flips reads-then-writes with ONE durable record through the
    replicated metadata plane (:data:`LAYOUT_MANIFEST_ID`). A write
    racing the watermark check lands in both layouts — it was
    dual-written like every other — so the re-verify inside the freeze
    is a bounded drain, never a redo.

``abort`` (any phase before the flip) stops the workers, discards the
queue and cursors, and leaves the old layout **byte-identical**: the
migration never wrote to it, only read its feed. After the flip, abort
refuses loudly — the system of record has moved.

Deployment note: in a real fleet the *mirror* role (queue append, on
the ingest path) lives in the event server process and the *coordinator*
role (drain + backfill + cutover) in ``pio migrate``; both speak through
the durable state directory, which is why every handoff here — queue
offset, backfill cursors, phase — is a file, never memory. The chaos
drill (``loadgen --migrate-drill``) kills and resumes the coordinator
across instances to pin exactly that.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import secrets
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..obs import flight
from ..obs.metrics import MetricsRegistry
from ..utils.durability import atomic_write_bytes, fsync_dir
from .event import Event

logger = logging.getLogger("predictionio.storage.migration")

__all__ = [
    "LAYOUT_MANIFEST_ID",
    "MigrationError",
    "MigrationFrozen",
    "MigrationState",
    "PartitionMigration",
    "PendingQueue",
    "PHASES",
    "active_layout",
    "open_migration",
]

#: phase order; the ``pio_migration_phase`` gauge exports the index
PHASES = (
    "idle",
    "dual_write",
    "backfill",
    "ready",
    "cutover",
    "done",
    "aborted",
)
_PHASE_INDEX = {name: i for i, name in enumerate(PHASES)}

#: phases in which acked writes are mirrored to the new layout
_MIRRORING = frozenset({"dual_write", "backfill", "ready", "cutover"})

#: the metadata-plane record the cutover flip writes: one replicated
#: manifest row (id, version="active") whose description carries the
#: new layout as JSON — readers resolve the active layout from the meta
#: partition's chain, exactly like every other replicated config
LAYOUT_MANIFEST_ID = "pio::event-layout"

_STATE_NAME = "migration.json"
_QUEUE_DIR = "mirror-queue"


class MigrationError(RuntimeError):
    """An invalid migration transition (cutover before the watermark,
    abort after the flip, start over a live migration) — always loud,
    never a silent no-op: every caller is an operator surface."""


class MigrationFrozen(MigrationError):
    """A write arrived inside the cutover freeze window. The event
    server maps this to 503 + ``Retry-After`` — the same shed contract
    as :class:`~predictionio_tpu.storage.remote.PartitionUnavailable`,
    because to a well-behaved client the freeze IS a brief partition
    unavailability with a bounded horizon."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


@dataclasses.dataclass
class MigrationState:
    """The durable coordinator state (``<state_dir>/migration.json``,
    written crash-safely). Everything a restarted coordinator needs:
    phase, both layouts, and the per-old-partition backfill cursors."""

    phase: str = "idle"
    migration_id: str = ""
    old_url: str = ""
    new_url: str = ""
    old_count: int = 1
    new_count: int = 1
    #: old partition index (str, JSON keys) -> last oplog seq backfilled
    cursors: Dict[str, int] = dataclasses.field(default_factory=dict)
    started_at_unix: float = 0.0
    flipped_at_unix: float = 0.0
    aborted_reason: str = ""

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: dict) -> "MigrationState":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})

    @classmethod
    def load(cls, path: str) -> Optional["MigrationState"]:
        try:
            with open(path) as fh:
                return cls.from_json(json.load(fh))
        except OSError:
            return None

    def save(self, path: str) -> None:
        atomic_write_bytes(
            path, json.dumps(self.to_json(), sort_keys=True).encode()
        )


class PendingQueue:
    """Durable mirror-write queue: append-only JSONL plus a drain
    cursor file. The append fsyncs before the writer returns — the
    mirror copy is part of the write's durability story even though it
    is never part of its *ack* — and the cursor advances only after the
    new layout applied the entry. Every entry is an idempotent op
    (resolved event ids → upsert; deletes keyed by id), so a crash
    between apply and cursor persist re-applies a suffix and converges,
    the same replay contract the oplog gives replicas."""

    def __init__(self, directory: str):
        os.makedirs(directory, exist_ok=True)
        self._dir = directory
        self._path = os.path.join(directory, "queue.jsonl")
        self._cursor_path = os.path.join(directory, "queue_cursor.json")
        self._lock = threading.Lock()
        self._offset = 0  # drained byte offset
        self.drained = 0
        try:
            with open(self._cursor_path) as fh:
                cur = json.load(fh)
            self._offset = int(cur.get("offset", 0))
            self.drained = int(cur.get("drained", 0))
        except OSError:
            pass
        self.appended = 0
        if os.path.exists(self._path):
            with open(self._path, "rb") as fh:
                self.appended = sum(1 for _ in fh)
        # unbuffered append handle: a completed append is visible to a
        # concurrent drain (coordinator instance) via the page cache
        self._fh = open(self._path, "ab", buffering=0)

    def append(self, entry: dict) -> None:
        """Durably enqueue one mirror op. Fsync per append: if the old
        layout acked the write, the mirror intent must survive a crash
        — losing it would silently strand the event on cutover."""
        line = (json.dumps(entry, separators=(",", ":")) + "\n").encode()
        with self._lock:
            self._fh.write(line)
            # pio: lint-ok[conc-blocking-under-lock] the fsync IS the ack barrier: a concurrent append must not reorder against this one's durability
            os.fsync(self._fh.fileno())
            self.appended += 1

    def pending(self) -> int:
        with self._lock:
            return self.appended - self.drained

    def drain(
        self, apply_fn: Callable[[dict], None], max_entries: int = 500
    ) -> int:
        """Apply up to ``max_entries`` undrained entries in order. Stops
        (without raising) at the first failing entry — a dead new-layout
        primary leaves the queue intact for the next round; ingest never
        sees it. Returns the number applied."""
        applied = 0
        with self._lock:
            offset = self._offset
        try:
            fh = open(self._path, "rb")
        except OSError:
            return 0
        with fh:
            fh.seek(offset)
            for _ in range(max_entries):
                line = fh.readline()
                if not line:
                    break
                try:
                    entry = json.loads(line)
                except ValueError:
                    # torn tail of a crashed append: everything after it
                    # is unreadable until the writer completes the line
                    break
                try:
                    apply_fn(entry)
                except Exception as exc:
                    logger.warning(
                        "mirror queue drain stalled (entry %d): %s",
                        self.drained + applied + 1, exc,
                    )
                    break
                applied += 1
                offset = fh.tell()
        if applied:
            with self._lock:
                self._offset = offset
                self.drained += applied
                drained = self.drained
            # cursor write outside the lock: only one coordinator
            # drains, so the snapshot cannot go backwards, and appends
            # (the hot ingest path) never wait out the rename
            atomic_write_bytes(
                self._cursor_path,
                json.dumps(
                    {"offset": offset, "drained": drained}
                ).encode(),
            )
        return applied

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:
            pass

    def discard(self) -> None:
        """Abort path: close and remove the queue files — the mirror
        intent dies with the migration, the old layout never needed it."""
        self.close()
        for path in (self._path, self._cursor_path):
            try:
                os.remove(path)
            except OSError:
                pass
        fsync_dir(self._dir)


def open_migration(
    state_dir: str,
    old_url: str = "",
    new_url: str = "",
    timeout: float = 10.0,
) -> "PartitionMigration":
    """The CLI's one construction point (``pio migrate``): resume a
    coordinator over ``state_dir`` with ``pio+ha://`` remote clients
    derived from the recorded layout URLs (``--old``/``--new`` only
    needed on the very first ``start``). The metadata plane rides the
    OLD layout's meta chain — both layouts share it, which is what
    makes the flip a replicated metadata write."""
    state = MigrationState.load(os.path.join(state_dir, _STATE_NAME))
    if state is not None:
        old_url = old_url or state.old_url
        new_url = new_url or state.new_url
    if not old_url or not new_url:
        raise MigrationError(
            "no layout URLs: pass --old and --new (none recorded in "
            f"{state_dir})"
        )
    from .remote import RemoteEventStore, RemoteMetadataStore

    return PartitionMigration(
        RemoteEventStore(old_url, timeout=timeout),
        RemoteEventStore(new_url, timeout=timeout),
        state_dir,
        old_url=old_url,
        new_url=new_url,
        metadata=RemoteMetadataStore(old_url, timeout=timeout),
    )


def active_layout(metadata) -> Optional[dict]:
    """The layout record the last cutover flipped to (None before any
    migration): ``{"url", "partitions", "migrationId", "flippedAtUnix"}``
    read from the replicated metadata plane."""
    try:
        m = metadata.manifest_get(LAYOUT_MANIFEST_ID, "active")
    except Exception:
        return None
    if m is None or not m.description:
        return None
    try:
        return json.loads(m.description)
    except ValueError:
        return None


class PartitionMigration:
    """Coordinator for one live migration old(N) → new(M).

    ``old_store`` / ``new_store`` are event-store clients (the
    ``pio+ha://`` :class:`~predictionio_tpu.storage.remote
    .RemoteEventStore`, or any store with the same ``insert`` /
    ``write`` / ``delete`` / ``init`` surface); each client routes to
    its *own* layout's owning partition internally, so this class never
    recomputes hash math. ``old_feeds`` are per-old-partition changefeed
    sources (:class:`~predictionio_tpu.continuous.watcher.LocalFeed` /
    ``RemoteFeed``); resolved lazily from ``old_url`` when omitted.

    Construction over an existing ``state_dir`` *resumes*: phase, queue
    offset and backfill cursors are all durable, so a coordinator killed
    mid-anything picks up where the files say."""

    def __init__(
        self,
        old_store,
        new_store,
        state_dir: str,
        *,
        old_url: str = "",
        new_url: str = "",
        old_feeds: Optional[Sequence] = None,
        metadata=None,
        clock: Callable[[], float] = time.monotonic,
        metrics: Optional[MetricsRegistry] = None,
    ):
        self.old_store = old_store
        self.new_store = new_store
        self.state_dir = state_dir
        os.makedirs(state_dir, exist_ok=True)
        self._state_path = os.path.join(state_dir, _STATE_NAME)
        self._metadata = metadata
        self._clock = clock
        self._lock = threading.Lock()
        self._dead = False
        self.writes_frozen = False
        state = MigrationState.load(self._state_path)
        self.state = state if state is not None else MigrationState(
            old_url=old_url,
            new_url=new_url,
            old_count=getattr(old_store, "partition_count", 1),
            new_count=getattr(new_store, "partition_count", 1),
        )
        if old_url and not self.state.old_url:
            self.state.old_url = old_url
        if new_url and not self.state.new_url:
            self.state.new_url = new_url
        self._feeds = list(old_feeds) if old_feeds is not None else None
        self.queue = PendingQueue(os.path.join(state_dir, _QUEUE_DIR))
        #: the store of record, swapped exactly once (in :meth:`cutover`,
        #: behind the verified watermark); one attribute read on the hot
        #: ingest path instead of a phase recompute per request
        self._active = (
            self.new_store if self.state.phase == "done" else self.old_store
        )
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._phase_gauge = self.metrics.gauge(
            "pio_migration_phase",
            "Live-migration phase index (order: " + ", ".join(PHASES) + ")",
        )
        self._lag_gauge = self.metrics.gauge(
            "pio_migration_backfill_lag_events",
            "Old-partition oplog ops not yet backfilled into the new "
            "layout, per old partition",
            labelnames=("partition",),
        )
        self._phase_gauge.set(_PHASE_INDEX[self.state.phase])

    # -- feeds ------------------------------------------------------------
    def _old_feeds(self) -> List:
        if self._feeds is None:
            from ..continuous.watcher import RemoteFeed
            from .partition import partition_primaries

            if not self.state.old_url:
                raise MigrationError(
                    "no old_feeds and no old_url to derive them from"
                )
            self._feeds = [
                RemoteFeed(url)
                for url in partition_primaries(self.state.old_url)
            ]
        return self._feeds

    # -- phase machinery --------------------------------------------------
    @property
    def phase(self) -> str:
        return self.state.phase

    @property
    def flipped(self) -> bool:
        return self.state.phase in ("done",)

    def _check_alive(self) -> None:
        if self._dead:
            raise MigrationError("coordinator instance was killed")

    def _set_phase(self, phase: str) -> None:
        self.state.phase = phase
        self.state.save(self._state_path)
        self._phase_gauge.set(_PHASE_INDEX[phase])
        flight.record(
            "migration", "storage.migration.phase",
            phase=phase, migrationId=self.state.migration_id,
        )

    def start(self) -> dict:
        """``idle`` → ``dual_write``: from this moment every acked write
        must be mirrored (:meth:`mirror`). Loud on re-entry — a second
        concurrent migration would fork the mirror queue."""
        self._check_alive()
        with self._lock:
            if self.state.phase != "idle":
                raise MigrationError(
                    f"migration already {self.state.phase} "
                    f"(id {self.state.migration_id or '?'})"
                )
            self.state.migration_id = secrets.token_hex(6)
            self.state.started_at_unix = time.time()
            self.state.cursors = {
                str(i): 0 for i in range(self.state.old_count)
            }
            self._set_phase("dual_write")
        return self.status()

    # -- the dual-write path ---------------------------------------------
    def mirroring(self) -> bool:
        return self.state.phase in _MIRRORING

    def check_frozen(self) -> None:
        """Raise :class:`MigrationFrozen` inside the cutover freeze —
        the event server calls this before acking any event write."""
        if self.writes_frozen:
            raise MigrationFrozen(
                "migration cutover in progress: writes are frozen for "
                "the final drain", retry_after_s=1.0,
            )

    def active_events(self):
        """The store of record: old until the flip, new after. The event
        server routes every event read and write through this."""
        return self._active

    def mirror(self, events: Sequence[Event], app_id: int) -> None:
        """Enqueue already-ACKED events for the new layout. Every event
        must carry its resolved id (the ack resolved it) — the queue
        replay and the backfill both upsert that id, which is the whole
        dedup story. Never raises into the ingest path: a queue append
        failure is recorded loudly instead (the backfill still covers
        the event, because it is in the old oplog)."""
        if not self.mirroring():
            return
        try:
            payload = []
            for e in events:
                if e.event_id is None:
                    raise ValueError(
                        "mirror requires resolved event ids (got an "
                        "id-less event) — mirror after the ack"
                    )
                payload.append(e.to_json_dict())
            self.queue.append(
                {"kind": "write", "app": int(app_id), "events": payload}
            )
        except Exception as exc:
            logger.error("migration mirror enqueue failed: %s", exc)
            flight.record(
                "migration", "storage.migration.mirror_failed",
                error=str(exc), app=int(app_id),
            )

    def mirror_delete(self, event_id: str, app_id: int) -> None:
        """Deletes mirror too — a delete acked on the old layout must
        not resurrect on the new one (the backfill also replays it, so
        this is latency, not correctness)."""
        if not self.mirroring():
            return
        try:
            self.queue.append(
                {"kind": "delete", "app": int(app_id), "eventId": event_id}
            )
        except Exception as exc:
            logger.error("migration mirror enqueue failed: %s", exc)
            flight.record(
                "migration", "storage.migration.mirror_failed",
                error=str(exc), app=int(app_id),
            )

    def write(self, events: Sequence[Event], app_id: int) -> List[str]:
        """Convenience full dual-write (the drill's writer path; the
        event server composes the same steps inline): ack on the active
        store, then mirror the resolved events. Returns the acked ids."""
        self.check_frozen()
        store = self.active_events()
        ids: List[str] = []
        resolved: List[Event] = []
        for e in events:
            event_id = store.insert(e, app_id)
            ids.append(event_id)
            resolved.append(
                e if e.event_id is not None
                else dataclasses.replace(e, event_id=event_id)
            )
        if not self.flipped:
            self.mirror(resolved, app_id)
        return ids

    def _apply_queue_entry(self, entry: dict) -> None:
        kind = entry.get("kind")
        if kind == "write":
            self.new_store.write(
                [Event.from_json_dict(d) for d in entry["events"]],
                entry["app"],
            )
        elif kind == "delete":
            self.new_store.delete(entry["eventId"], entry["app"])
        else:
            raise MigrationError(f"unknown mirror queue entry {kind!r}")

    def drain_queue(self, max_entries: int = 500) -> int:
        self._check_alive()
        return self.queue.drain(self._apply_queue_entry, max_entries)

    # -- backfill ---------------------------------------------------------
    def begin_backfill(self) -> dict:
        self._check_alive()
        with self._lock:
            if self.state.phase != "dual_write":
                raise MigrationError(
                    f"backfill starts from dual_write, not "
                    f"{self.state.phase}"
                )
            self._set_phase("backfill")
        return self.status()

    def _apply_backfill_op(self, op: dict) -> None:
        """Replay one old-oplog op into the new layout. Only event ops:
        metadata and models live on the meta chain, which both layouts
        share — migrating them here would double-apply. Idempotent by
        the same argument as changefeed.apply_op (resolved ids)."""
        kind = op.get("kind")
        if kind == "event_insert":
            self.new_store.insert(
                Event.from_json_dict(op["event"]), op["app"]
            )
        elif kind == "event_write":
            self.new_store.write(
                [Event.from_json_dict(d) for d in op["events"]], op["app"]
            )
        elif kind == "event_delete":
            self.new_store.delete(op["eventId"], op["app"])
        elif kind == "event_init":
            self.new_store.init(op["app"])
        elif kind == "event_remove":
            self.new_store.remove(op["app"])
        # meta / model ops: deliberately skipped (see docstring)

    def backfill_step(self, max_ops: int = 500) -> dict:
        """One bounded backfill round across every old partition: fetch
        from the durable cursor, apply, persist the cursor *after* the
        apply (crash between = idempotent re-apply). Returns per-
        partition progress; a partition whose fetch or apply fails is
        reported stalled and retried next round — one dead primary
        never wedges the others' progress."""
        self._check_alive()
        if self.state.phase not in ("backfill", "ready", "cutover"):
            raise MigrationError(
                f"backfill_step in phase {self.state.phase}"
            )
        progress: Dict[str, dict] = {}
        feeds = self._old_feeds()
        for i, feed in enumerate(feeds):
            key = str(i)
            cursor = int(self.state.cursors.get(key, 0))
            row = {"cursor": cursor, "applied": 0, "stalled": False}
            try:
                batch = feed.fetch(cursor, max_ops)
                for change in batch.get("changes", []):
                    self._apply_backfill_op(change["op"])
                    cursor = int(change["seq"])
                    row["applied"] += 1
                head = int(batch.get("lastSeq", cursor))
            except Exception as exc:
                logger.warning(
                    "backfill partition %d stalled at seq %d: %s",
                    i, cursor, exc,
                )
                row["stalled"] = True
                row["error"] = str(exc)
                head = cursor
            if row["applied"]:
                self.state.cursors[key] = cursor
                self.state.save(self._state_path)
            row["cursor"] = cursor
            row["head"] = max(head, cursor)
            row["lag"] = max(0, row["head"] - cursor)
            self._lag_gauge.set(row["lag"], partition=key)
            progress[key] = row
        return progress

    # -- watermark + cutover ----------------------------------------------
    def watermark(self) -> dict:
        """The cutover precondition, verified per keyspace slice: every
        old partition's backfill cursor has reached its feed head, AND
        the mirror queue is drained. Read-only — callers decide what to
        do about a false verdict."""
        partitions: Dict[str, dict] = {}
        ok = self.state.phase in ("backfill", "ready", "cutover")
        for i, feed in enumerate(self._old_feeds()):
            key = str(i)
            cursor = int(self.state.cursors.get(key, 0))
            try:
                cp = feed.checkpoint()
                head = int(cp.get("seq", cp.get("lastSeq", 0)))
                row = {"cursor": cursor, "head": head,
                       "lag": max(0, head - cursor)}
            except Exception as exc:
                row = {"cursor": cursor, "head": None, "lag": None,
                       "error": str(exc)}
                ok = False
            if row.get("lag") != 0:
                ok = False
            self._lag_gauge.set(row.get("lag") or 0, partition=key)
            partitions[key] = row
        pending = self.queue.pending()
        if pending:
            ok = False
        return {"ok": ok, "partitions": partitions, "queuePending": pending}

    def pump(self, max_ops: int = 500) -> dict:
        """One coordinator tick: drain the mirror queue, advance the
        backfill, and promote ``backfill`` → ``ready`` the first time
        the watermark verifies. This is the unit the drill kills and
        resumes around — everything it advances is durable."""
        self._check_alive()
        out: dict = {"phase": self.state.phase}
        out["queueDrained"] = self.drain_queue(max_ops)
        if self.state.phase == "dual_write":
            # the first coordinator tick commits to the backfill; the
            # operator's mirror-health window is between start and here
            self.begin_backfill()
            out["phase"] = self.state.phase
        if self.state.phase in ("backfill", "ready", "cutover"):
            out["backfill"] = self.backfill_step(max_ops)
        if self.state.phase == "backfill":
            wm = self.watermark()
            out["watermark"] = wm
            if wm["ok"]:
                with self._lock:
                    self._set_phase("ready")
                out["phase"] = "ready"
        return out

    def cutover(self, timeout_s: float = 30.0) -> dict:
        """Freeze, final drain, re-verify, flip. The flip writes the
        new layout through the replicated metadata plane and only then
        advances the durable phase to ``done`` — a crash between leaves
        phase ``cutover`` with the manifest already new, and resume
        completes the phase write (the manifest is the authority, the
        phase file is the coordinator's bookmark). Raises (and thaws)
        if the watermark cannot verify inside ``timeout_s``."""
        self._check_alive()
        with self._lock:
            if self.state.phase not in ("ready", "backfill", "cutover"):
                raise MigrationError(
                    f"cutover from phase {self.state.phase!r} — run the "
                    "backfill to the watermark first"
                )
        self.writes_frozen = True
        try:
            deadline = self._clock() + timeout_s
            while True:
                # the race-window write: anything acked between the
                # caller's watermark check and this freeze was dual-
                # written like every other write, so the final drain
                # below is bounded by the freeze, not re-opened by it
                self.drain_queue()
                if self.state.phase in ("backfill", "ready"):
                    self.backfill_step()
                wm = self.watermark()
                if wm["ok"]:
                    break
                if self._clock() >= deadline:
                    raise MigrationError(
                        "cutover watermark did not verify within "
                        f"{timeout_s:.1f}s: {json.dumps(wm)}"
                    )
                time.sleep(0.01)
            with self._lock:
                self._set_phase("cutover")
                self._flip()
                self.state.flipped_at_unix = time.time()
                self._set_phase("done")
                # reads and writes flip together, behind the watermark
                # this function just verified and the drained queue —
                # the evidence robust-cutover-no-watermark demands
                if self.flipped:
                    self._active = self.new_store
                else:
                    self._active = self.old_store
        finally:
            self.writes_frozen = False
        flight.record(
            "migration", "storage.migration.cutover",
            migrationId=self.state.migration_id,
            oldCount=self.state.old_count, newCount=self.state.new_count,
        )
        return self.status()

    def _flip(self) -> None:
        """The atomic read+write flip: after this, :meth:`active_events`
        answers the new store. Guarded by the watermark verified in
        :meth:`cutover` (queue drained + every keyspace slice caught
        up) — flipping without it would strand the undrained suffix on
        a layout nothing reads anymore."""
        if self._metadata is not None:
            from .metadata import EngineManifest

            self._metadata.manifest_update(
                EngineManifest(
                    id=LAYOUT_MANIFEST_ID,
                    version="active",
                    name="event-layout",
                    description=json.dumps(
                        {
                            "url": self.state.new_url,
                            "partitions": self.state.new_count,
                            "migrationId": self.state.migration_id,
                            "flippedAtUnix": time.time(),
                        }
                    ),
                )
            )

    # -- abort / drill helpers --------------------------------------------
    def abort(self, reason: str = "") -> dict:
        """Safe before the flip, refused loudly after. Discards the
        mirror queue and cursors; the old layout is untouched (the
        migration only ever *read* it), so service continues exactly as
        before ``start``."""
        with self._lock:
            if self.state.phase in ("done",):
                raise MigrationError(
                    "cannot abort: cutover already flipped to the new "
                    "layout — migrate back instead"
                )
            self.queue.discard()
            self.state.cursors = {}
            self.state.aborted_reason = reason or "operator abort"
            self._set_phase("aborted")
        flight.record(
            "migration", "storage.migration.abort",
            migrationId=self.state.migration_id, reason=reason,
        )
        logger.warning(
            "migration %s aborted (%s): old layout remains the system "
            "of record", self.state.migration_id, reason,
        )
        return self.status()

    def kill(self) -> None:
        """Drill helper: simulate the coordinator process dying. The
        instance refuses further coordination (writers keep their queue
        handle — the mirror role survives in the event server); a new
        instance over the same ``state_dir`` resumes from the durable
        cursors."""
        self._dead = True

    def status(self) -> dict:
        queue_pending = self.queue.pending()
        return {
            "phase": self.state.phase,
            "migrationId": self.state.migration_id,
            "oldUrl": self.state.old_url,
            "newUrl": self.state.new_url,
            "oldCount": self.state.old_count,
            "newCount": self.state.new_count,
            "cursors": dict(self.state.cursors),
            "queuePending": queue_pending,
            "queueAppended": self.queue.appended,
            "queueDrained": self.queue.drained,
            "abortedReason": self.state.aborted_reason or None,
        }

    def close(self) -> None:
        self.queue.close()
