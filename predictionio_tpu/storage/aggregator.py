"""Property aggregation: fold ``$set/$unset/$delete`` into entity state.

Rebuild of the reference's aggregation monoid
(``data/src/main/scala/io/prediction/data/storage/PEventAggregator.scala:27-209``
and ``LEventAggregator.scala``): each special event becomes an :class:`EventOp`;
ops combine associatively and commutatively (per-field latest-timestamp wins),
so aggregation order never matters — the analogue of Spark's ``aggregateByKey``
is a plain fold here, and a sharded ``jax`` reduction at scale.

Resolution rules (``PEventAggregator.scala:115-146``):

- No ``$set`` ever seen → entity has no property map (``None``).
- A field is dropped if an ``$unset`` of it is at a time >= the field's set time.
- A ``$delete`` at time >= the *latest* ``$set`` time deletes the entity;
  otherwise it drops every field whose set time <= the delete time.
- ``first_updated`` / ``last_updated`` span only the special events seen.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
from typing import Any, Dict, Iterable, Optional

from .data_map import PropertyMap
from .event import SPECIAL_EVENTS, Event, to_millis as _millis


@dataclasses.dataclass(frozen=True)
class PropTime:
    """A field value with the time it was set (``PEventAggregator.scala:27``)."""

    value: Any
    t: int  # epoch millis


@dataclasses.dataclass(frozen=True)
class EventOp:
    """Commutative monoid of property operations (``PEventAggregator.scala:87``)."""

    set_fields: Optional[Dict[str, PropTime]] = None
    set_t: int = 0  # latest $set event time (valid when set_fields is not None)
    unset_fields: Optional[Dict[str, int]] = None
    delete_t: Optional[int] = None
    first_updated: Optional[_dt.datetime] = None
    last_updated: Optional[_dt.datetime] = None

    @classmethod
    def identity(cls) -> "EventOp":
        return cls()

    @classmethod
    def from_event(cls, e: Event) -> "EventOp":
        """``EventOp.apply`` (``PEventAggregator.scala:153-186``)."""
        t = _millis(e.event_time)
        if e.event == "$set":
            return cls(
                set_fields={k: PropTime(v, t) for k, v in e.properties.items()},
                set_t=t,
                first_updated=e.event_time,
                last_updated=e.event_time,
            )
        if e.event == "$unset":
            return cls(
                unset_fields={k: t for k in e.properties},
                first_updated=e.event_time,
                last_updated=e.event_time,
            )
        if e.event == "$delete":
            return cls(
                delete_t=t,
                first_updated=e.event_time,
                last_updated=e.event_time,
            )
        return cls()

    def combine(self, other: "EventOp") -> "EventOp":
        """Monoid ``++`` (``PEventAggregator.scala:95-110``)."""
        # $set merge: per-field latest time wins; latest set time kept.
        if self.set_fields is None:
            set_fields, set_t = other.set_fields, other.set_t
        elif other.set_fields is None:
            set_fields, set_t = self.set_fields, self.set_t
        else:
            merged = dict(self.set_fields)
            for k, pt in other.set_fields.items():
                cur = merged.get(k)
                if cur is None or pt.t > cur.t:
                    merged[k] = pt
            set_fields, set_t = merged, max(self.set_t, other.set_t)

        # $unset merge: per-field latest time wins.
        if self.unset_fields is None:
            unset_fields = other.unset_fields
        elif other.unset_fields is None:
            unset_fields = self.unset_fields
        else:
            unset_fields = dict(self.unset_fields)
            for k, t in other.unset_fields.items():
                if t > unset_fields.get(k, -1):
                    unset_fields[k] = t

        delete_ts = [t for t in (self.delete_t, other.delete_t) if t is not None]
        firsts = [d for d in (self.first_updated, other.first_updated) if d]
        lasts = [d for d in (self.last_updated, other.last_updated) if d]
        return EventOp(
            set_fields=set_fields,
            set_t=set_t,
            unset_fields=unset_fields,
            delete_t=max(delete_ts) if delete_ts else None,
            first_updated=min(firsts) if firsts else None,
            last_updated=max(lasts) if lasts else None,
        )

    __add__ = combine

    def to_property_map(self) -> Optional[PropertyMap]:
        """``toPropertyMap`` (``PEventAggregator.scala:115-146``)."""
        if self.set_fields is None:
            return None
        fields = dict(self.set_fields)

        # Fields unset at/after their set time are dropped. (The reference
        # indexes set.fields(k) directly; keys never $set are simply absent.)
        if self.unset_fields:
            for k, unset_t in self.unset_fields.items():
                pt = fields.get(k)
                if pt is not None and unset_t >= pt.t:
                    del fields[k]

        if self.delete_t is not None:
            if self.delete_t >= self.set_t:
                return None  # entity deleted after its last $set
            fields = {k: pt for k, pt in fields.items() if pt.t > self.delete_t}

        assert self.first_updated is not None and self.last_updated is not None
        return PropertyMap(
            {k: pt.value for k, pt in fields.items()},
            first_updated=self.first_updated,
            last_updated=self.last_updated,
        )


#: Event names that participate in aggregation (``PEventAggregator.scala:191``).
AGGREGATOR_EVENT_NAMES = tuple(sorted(SPECIAL_EVENTS))


def aggregate_properties(
    events: Iterable[Event],
) -> Dict[str, PropertyMap]:
    """Fold events into per-entity property maps.

    The analogue of ``PEventAggregator.aggregateProperties``
    (``PEventAggregator.scala:193-209``) and
    ``LEventAggregator.aggregateProperties``; callers are expected to have
    filtered to one (entityType) and the special event names.
    """
    ops: Dict[str, EventOp] = {}
    for e in events:
        op = EventOp.from_event(e)
        cur = ops.get(e.entity_id)
        ops[e.entity_id] = op if cur is None else cur.combine(op)
    out: Dict[str, PropertyMap] = {}
    for entity_id, op in ops.items():
        pm = op.to_property_map()
        if pm is not None:
            out[entity_id] = pm
    return out


def aggregate_single(events: Iterable[Event]) -> Optional[PropertyMap]:
    """Aggregate events of a single entity (``LEventAggregator.scala``
    ``aggregatePropertiesSingle``)."""
    acc = EventOp.identity()
    for e in events:
        acc = acc.combine(EventOp.from_event(e))
    return acc.to_property_map()
