"""Storage registry: environment-driven backend wiring.

Rebuild of the reference's ``Storage`` object
(``data/src/main/scala/io/prediction/data/storage/Storage.scala:33-302``):
sources are declared by ``PIO_STORAGE_SOURCES_<NAME>_TYPE`` (+ ``_PATH`` here,
instead of hosts/ports), and the three repositories are bound by
``PIO_STORAGE_REPOSITORIES_{METADATA,MODELDATA,EVENTDATA}_{NAME,SOURCE}``.

Remote sources scale out with ``PIO_STORAGE_SOURCES_<NAME>_NODES``
(one HA chain: ``primary:7079,replica:7079``) or, for the partitioned
write path (``docs/storage.md#partitioning``),
``PIO_STORAGE_SOURCES_<NAME>_PARTITIONS`` — ``;``-separated HA chains,
one per keyspace partition in index order
(``p0:7079,p0r:7079;p1:7079,p1r:7079``). Event writes then route by
the (app, entity) partition hash; metadata and models stay on the
first chain (the meta partition).
Clients are constructed lazily and cached per source
(``Storage.scala:124-174``); ``verify_all_data_objects`` backs the ``status``
CLI command (``Storage.scala:230-250``).

Default wiring (no env vars): a single SQLite source rooted at
``$PIO_FS_BASEDIR`` (default ``~/.predictionio_tpu``), so a fresh checkout
works with zero configuration.
"""

from __future__ import annotations

import os
import re
import threading
from typing import Dict, Optional

from .backends import (
    BackendFamily,
    BackendLookupError,
    make_store,
    register_backend,
)
from .event import Event, utcnow
from .events import EventStore
from .metadata import MetadataStore
from .model_store import LocalFSModelStore, Model, ModelStore, SqliteModelStore
from .sqlite_events import SqliteEventStore

_SOURCE_RE = re.compile(r"^PIO_STORAGE_SOURCES_([^_]+)_TYPE$")

REPO_METADATA = "METADATA"
REPO_MODELDATA = "MODELDATA"
REPO_EVENTDATA = "EVENTDATA"


class StorageError(Exception):
    """Configuration or client-construction failure (``Storage.scala:61``)."""


def base_dir(env: Optional[Dict[str, str]] = None) -> str:
    e = env if env is not None else os.environ
    return e.get(
        "PIO_FS_BASEDIR", os.path.join(os.path.expanduser("~"), ".predictionio_tpu")
    )


def _conf_root(conf: Dict[str, str]) -> str:
    return conf.get("path") or base_dir()


def _native_events(conf: Dict[str, str]) -> EventStore:
    try:
        from .native_events import NativeEventStore
    except ImportError as exc:
        raise StorageError(
            "native event store backend is not built "
            f"(predictionio_tpu.storage.native_events): {exc}"
        ) from exc
    return NativeEventStore(
        os.path.join(_conf_root(conf), "events_native"),
        # PIO_STORAGE_SOURCES_<N>_WRITER_ID: give each ingest process its
        # own append segment (multi-writer scaling; see NativeEventStore)
        writer_id=conf.get("writer_id"),
    )


# Built-in families (the analogue of the reference's in-tree backend
# packages hbase/elasticsearch/localfs/hdfs, registered here instead of
# discovered by classname). Third-party families self-register on import —
# see backends.resolve_backend for the discovery order.
register_backend(
    BackendFamily(
        name="sqlite",
        events=lambda c: SqliteEventStore(os.path.join(_conf_root(c), "events.db")),
        metadata=lambda c: MetadataStore(os.path.join(_conf_root(c), "metadata.db")),
        models=lambda c: SqliteModelStore(os.path.join(_conf_root(c), "models.db")),
    )
)
register_backend(
    BackendFamily(
        name="localfs",
        events=lambda c: SqliteEventStore(os.path.join(_conf_root(c), "events.db")),
        metadata=lambda c: MetadataStore(os.path.join(_conf_root(c), "metadata.db")),
        models=lambda c: LocalFSModelStore(os.path.join(_conf_root(c), "models")),
    )
)
register_backend(
    BackendFamily(
        name="memory",
        events=lambda c: SqliteEventStore(":memory:"),
        metadata=lambda c: MetadataStore(":memory:"),
        models=lambda c: SqliteModelStore(":memory:"),
    )
)
register_backend(BackendFamily(name="native", events=_native_events))


def make_event_store(stype: str, root: str) -> EventStore:
    """Event-store factory (used by the registry and by ``pio upgrade``, so
    the two can never diverge). Thin wrapper over the family table."""
    try:
        return make_store(stype, "events", {"type": stype, "path": root})
    except BackendLookupError as exc:
        raise StorageError(str(exc)) from exc


class StorageRegistry:
    """Lazily-constructed, cached storage clients keyed by source name."""

    def __init__(self, env: Optional[Dict[str, str]] = None):
        self._env = dict(env) if env is not None else dict(os.environ)
        self._lock = threading.RLock()
        self._event_stores: Dict[str, EventStore] = {}
        self._metadata_stores: Dict[str, MetadataStore] = {}
        self._model_stores: Dict[str, ModelStore] = {}
        self._sources = self._parse_sources()

    # -- config parsing (Storage.scala:38-51,96-121) ----------------------
    def _parse_sources(self) -> Dict[str, Dict[str, str]]:
        sources: Dict[str, Dict[str, str]] = {}
        for key, value in self._env.items():
            m = _SOURCE_RE.match(key)
            if not m:
                continue
            name = m.group(1)
            prefix = f"PIO_STORAGE_SOURCES_{name}_"
            conf = {
                k[len(prefix):].lower(): v
                for k, v in self._env.items()
                if k.startswith(prefix)
            }
            sources[name] = conf
        if not sources:
            root = base_dir(self._env)
            sources["LOCAL"] = {"type": "sqlite", "path": root}
        return sources

    def _repo_source_name(self, repo: str) -> str:
        name = self._env.get(f"PIO_STORAGE_REPOSITORIES_{repo}_SOURCE")
        if name is None:
            if len(self._sources) == 1:
                return next(iter(self._sources))
            raise StorageError(
                f"Repository {repo} has no PIO_STORAGE_REPOSITORIES_{repo}_SOURCE "
                f"and multiple sources are configured: {sorted(self._sources)}"
            )
        if name not in self._sources:
            raise StorageError(
                f"Repository {repo} references undefined source {name!r} "
                f"(defined: {sorted(self._sources)})"
            )
        return name

    def _source_conf(self, name: str) -> Dict[str, str]:
        return self._sources[name]

    # -- repository accessors (Storage.scala:252-276) ---------------------
    def _get_store(self, repo: str, repo_kind: str, cache: Dict[str, object]):
        name = self._repo_source_name(repo)
        with self._lock:
            if name not in cache:
                conf = dict(self._source_conf(name))
                conf.setdefault("path", base_dir(self._env))
                try:
                    cache[name] = make_store(
                        conf.get("type", "sqlite"), repo_kind, conf
                    )
                except BackendLookupError as exc:
                    raise StorageError(str(exc)) from exc
            return cache[name]

    def get_events(self) -> EventStore:
        return self._get_store(REPO_EVENTDATA, "events", self._event_stores)

    def get_metadata(self) -> MetadataStore:
        return self._get_store(REPO_METADATA, "metadata", self._metadata_stores)

    def get_models(self) -> ModelStore:
        return self._get_store(REPO_MODELDATA, "models", self._model_stores)

    # -- verification (pio status; Storage.scala:230-250) ------------------
    def verify_all_data_objects(self) -> Dict[str, bool]:
        """Touch every repository with a live operation, incl. a test write."""
        results: Dict[str, bool] = {}
        try:
            md = self.get_metadata()
            md.app_get_all()
            results["metadata"] = True
        except Exception:
            results["metadata"] = False
        try:
            ms = self.get_models()
            probe = Model(id="pio-status-probe", models=b"probe")
            ms.insert(probe)
            ok = ms.get(probe.id)
            ms.delete(probe.id)
            results["modeldata"] = ok is not None and ok.models == b"probe"
        except Exception:
            results["modeldata"] = False
        try:
            ev = self.get_events()
            ev.init(0)
            eid = ev.insert(
                Event(
                    event="$set",
                    entity_type="pio_pr",
                    entity_id="status-probe",
                    event_time=utcnow(),
                ),
                0,
            )
            ok2 = ev.get(eid, 0) is not None
            ev.delete(eid, 0)
            results["eventdata"] = ok2
        except Exception:
            results["eventdata"] = False
        return results


_default_registry: Optional[StorageRegistry] = None
_default_lock = threading.Lock()


def get_registry(refresh: bool = False) -> StorageRegistry:
    """Process-wide registry built from ``os.environ`` (``Storage`` object)."""
    global _default_registry
    with _default_lock:
        if _default_registry is None or refresh:
            _default_registry = StorageRegistry()
        return _default_registry
