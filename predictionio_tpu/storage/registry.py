"""Storage registry: environment-driven backend wiring.

Rebuild of the reference's ``Storage`` object
(``data/src/main/scala/io/prediction/data/storage/Storage.scala:33-302``):
sources are declared by ``PIO_STORAGE_SOURCES_<NAME>_TYPE`` (+ ``_PATH`` here,
instead of hosts/ports), and the three repositories are bound by
``PIO_STORAGE_REPOSITORIES_{METADATA,MODELDATA,EVENTDATA}_{NAME,SOURCE}``.
Clients are constructed lazily and cached per source
(``Storage.scala:124-174``); ``verify_all_data_objects`` backs the ``status``
CLI command (``Storage.scala:230-250``).

Default wiring (no env vars): a single SQLite source rooted at
``$PIO_FS_BASEDIR`` (default ``~/.predictionio_tpu``), so a fresh checkout
works with zero configuration.
"""

from __future__ import annotations

import os
import re
import threading
from typing import Dict, Optional

from .event import Event, utcnow
from .events import EventStore
from .metadata import MetadataStore
from .model_store import LocalFSModelStore, Model, ModelStore, SqliteModelStore
from .sqlite_events import SqliteEventStore

_SOURCE_RE = re.compile(r"^PIO_STORAGE_SOURCES_([^_]+)_TYPE$")

REPO_METADATA = "METADATA"
REPO_MODELDATA = "MODELDATA"
REPO_EVENTDATA = "EVENTDATA"


class StorageError(Exception):
    """Configuration or client-construction failure (``Storage.scala:61``)."""


def base_dir(env: Optional[Dict[str, str]] = None) -> str:
    e = env if env is not None else os.environ
    return e.get(
        "PIO_FS_BASEDIR", os.path.join(os.path.expanduser("~"), ".predictionio_tpu")
    )


def make_event_store(stype: str, root: str) -> EventStore:
    """Event-store factory: the single place mapping a source ``type`` string
    to a backend and its on-disk layout (used by the registry and by
    ``pio upgrade``, so the two can never diverge)."""
    if stype in ("sqlite", "localfs"):
        return SqliteEventStore(os.path.join(root, "events.db"))
    if stype == "memory":
        return SqliteEventStore(":memory:")
    if stype == "native":
        try:
            from .native_events import NativeEventStore
        except ImportError as exc:
            raise StorageError(
                "native event store backend is not built "
                f"(predictionio_tpu.storage.native_events): {exc}"
            ) from exc
        return NativeEventStore(os.path.join(root, "events_native"))
    raise StorageError(f"Unknown event store type {stype!r}")


class StorageRegistry:
    """Lazily-constructed, cached storage clients keyed by source name."""

    def __init__(self, env: Optional[Dict[str, str]] = None):
        self._env = dict(env) if env is not None else dict(os.environ)
        self._lock = threading.RLock()
        self._event_stores: Dict[str, EventStore] = {}
        self._metadata_stores: Dict[str, MetadataStore] = {}
        self._model_stores: Dict[str, ModelStore] = {}
        self._sources = self._parse_sources()

    # -- config parsing (Storage.scala:38-51,96-121) ----------------------
    def _parse_sources(self) -> Dict[str, Dict[str, str]]:
        sources: Dict[str, Dict[str, str]] = {}
        for key, value in self._env.items():
            m = _SOURCE_RE.match(key)
            if not m:
                continue
            name = m.group(1)
            prefix = f"PIO_STORAGE_SOURCES_{name}_"
            conf = {
                k[len(prefix):].lower(): v
                for k, v in self._env.items()
                if k.startswith(prefix)
            }
            sources[name] = conf
        if not sources:
            root = base_dir(self._env)
            sources["LOCAL"] = {"type": "sqlite", "path": root}
        return sources

    def _repo_source_name(self, repo: str) -> str:
        name = self._env.get(f"PIO_STORAGE_REPOSITORIES_{repo}_SOURCE")
        if name is None:
            if len(self._sources) == 1:
                return next(iter(self._sources))
            raise StorageError(
                f"Repository {repo} has no PIO_STORAGE_REPOSITORIES_{repo}_SOURCE "
                f"and multiple sources are configured: {sorted(self._sources)}"
            )
        if name not in self._sources:
            raise StorageError(
                f"Repository {repo} references undefined source {name!r} "
                f"(defined: {sorted(self._sources)})"
            )
        return name

    def _source_conf(self, name: str) -> Dict[str, str]:
        return self._sources[name]

    def _source_path(self, name: str, filename: str) -> str:
        conf = self._source_conf(name)
        root = conf.get("path", base_dir(self._env))
        return os.path.join(root, filename)

    # -- repository accessors (Storage.scala:252-276) ---------------------
    def get_events(self) -> EventStore:
        name = self._repo_source_name(REPO_EVENTDATA)
        with self._lock:
            if name not in self._event_stores:
                conf = self._source_conf(name)
                self._event_stores[name] = make_event_store(
                    conf.get("type", "sqlite"),
                    conf.get("path", base_dir(self._env)),
                )
            return self._event_stores[name]

    def get_metadata(self) -> MetadataStore:
        name = self._repo_source_name(REPO_METADATA)
        with self._lock:
            if name not in self._metadata_stores:
                conf = self._source_conf(name)
                stype = conf.get("type", "sqlite")
                if stype == "memory":
                    self._metadata_stores[name] = MetadataStore(":memory:")
                elif stype in ("sqlite", "localfs"):
                    self._metadata_stores[name] = MetadataStore(
                        self._source_path(name, "metadata.db")
                    )
                else:
                    raise StorageError(f"Unknown metadata store type {stype!r}")
            return self._metadata_stores[name]

    def get_models(self) -> ModelStore:
        name = self._repo_source_name(REPO_MODELDATA)
        with self._lock:
            if name not in self._model_stores:
                conf = self._source_conf(name)
                stype = conf.get("type", "sqlite")
                if stype == "localfs":
                    self._model_stores[name] = LocalFSModelStore(
                        self._source_path(name, "models")
                    )
                elif stype == "memory":
                    self._model_stores[name] = SqliteModelStore(":memory:")
                elif stype == "sqlite":
                    self._model_stores[name] = SqliteModelStore(
                        self._source_path(name, "models.db")
                    )
                else:
                    raise StorageError(f"Unknown model store type {stype!r}")
            return self._model_stores[name]

    # -- verification (pio status; Storage.scala:230-250) ------------------
    def verify_all_data_objects(self) -> Dict[str, bool]:
        """Touch every repository with a live operation, incl. a test write."""
        results: Dict[str, bool] = {}
        try:
            md = self.get_metadata()
            md.app_get_all()
            results["metadata"] = True
        except Exception:
            results["metadata"] = False
        try:
            ms = self.get_models()
            probe = Model(id="pio-status-probe", models=b"probe")
            ms.insert(probe)
            ok = ms.get(probe.id)
            ms.delete(probe.id)
            results["modeldata"] = ok is not None and ok.models == b"probe"
        except Exception:
            results["modeldata"] = False
        try:
            ev = self.get_events()
            ev.init(0)
            eid = ev.insert(
                Event(
                    event="$set",
                    entity_type="pio_pr",
                    entity_id="status-probe",
                    event_time=utcnow(),
                ),
                0,
            )
            ok2 = ev.get(eid, 0) is not None
            ev.delete(eid, 0)
            results["eventdata"] = ok2
        except Exception:
            results["eventdata"] = False
        return results


_default_registry: Optional[StorageRegistry] = None
_default_lock = threading.Lock()


def get_registry(refresh: bool = False) -> StorageRegistry:
    """Process-wide registry built from ``os.environ`` (``Storage`` object)."""
    global _default_registry
    with _default_lock:
        if _default_registry is None or refresh:
            _default_registry = StorageRegistry()
        return _default_registry
