"""Remote storage backend family (type ``remote``).

Client half of the server-mode storage pair (``storage/storage_server.py``)
— the rebuild's analogue of the reference's networked backends, where every
store is a client to a storage service (HBase ``StorageClient`` holding an
HConnection, ES ``StorageClient`` holding a ``TransportClient``;
``data/src/main/scala/io/prediction/data/storage/hbase/StorageClient.scala``,
``elasticsearch/StorageClient.scala``). Source conf keys::

    PIO_STORAGE_SOURCES_<NAME>_TYPE=remote
    PIO_STORAGE_SOURCES_<NAME>_HOST=10.0.0.2     (default 127.0.0.1)
    PIO_STORAGE_SOURCES_<NAME>_PORT=7079

This module self-registers the family on import: the registry's
``resolve_backend`` imports ``predictionio_tpu.storage.remote`` the first
time it meets ``type=remote`` — nothing in ``registry.py`` names this
backend (the pluggability contract, ``Storage.scala:176-217``).

Event scans stream as ndjson, so ``find`` over a huge app yields in bounded
memory on both sides.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Iterator, Optional

from .backends import BackendFamily, SourceConf, register_backend
from .event import Event
from .events import EventFilter, EventStore
from .model_store import Model, ModelStore
from .storage_server import DEFAULT_PORT, METADATA_RPC_METHODS
from .wire import decode, encode


class RemoteStorageError(Exception):
    """Transport or server-side failure, with the server's message.
    ``code`` is the HTTP status, or ``None`` for transport errors."""

    def __init__(self, message: str, code: Optional[int] = None):
        super().__init__(message)
        self.code = code


def _request(
    url: str, method: str = "GET", body: Optional[bytes] = None, timeout: float = 60.0
):
    req = urllib.request.Request(url, data=body, method=method)
    if body is not None:
        req.add_header("Content-Type", "application/json")
    try:
        return urllib.request.urlopen(req, timeout=timeout)
    except urllib.error.HTTPError as exc:
        detail = exc.read().decode("utf-8", "replace")[:500]
        raise RemoteStorageError(
            f"{method} {url} → HTTP {exc.code}: {detail}", code=exc.code
        ) from exc
    except urllib.error.URLError as exc:
        raise RemoteStorageError(f"{method} {url} unreachable: {exc.reason}") from exc


def _json(resp) -> dict:
    return json.loads(resp.read())


class RemoteEventStore(EventStore):
    """``EventStore`` over the storage server's /events routes."""

    def __init__(self, base_url: str, timeout: float = 60.0):
        # 60 s default mirrors the reference LEvents op timeout
        # (LEvents.scala:35).
        self._base = base_url.rstrip("/")
        self._timeout = timeout

    def _url(self, app_id: int, suffix: str = "") -> str:
        return f"{self._base}/events/{app_id}{suffix}"

    def init(self, app_id: int) -> bool:
        with _request(self._url(app_id, "/init"), "POST", b"{}", self._timeout) as r:
            return bool(_json(r)["ok"])

    def remove(self, app_id: int) -> bool:
        with _request(self._url(app_id, "/remove"), "POST", b"{}", self._timeout) as r:
            return bool(_json(r)["ok"])

    def insert(self, event: Event, app_id: int) -> str:
        body = json.dumps(event.to_json_dict()).encode()
        with _request(self._url(app_id), "POST", body, self._timeout) as r:
            return _json(r)["eventId"]

    def get(self, event_id: str, app_id: int) -> Optional[Event]:
        try:
            with _request(self._url(app_id, f"/{event_id}"), timeout=self._timeout) as r:
                return Event.from_json_dict(_json(r))
        except RemoteStorageError as exc:
            if exc.code == 404:
                return None
            raise

    def delete(self, event_id: str, app_id: int) -> bool:
        with _request(
            self._url(app_id, f"/{event_id}"), "DELETE", timeout=self._timeout
        ) as r:
            return bool(_json(r)["found"])

    def find(
        self, app_id: int, filter: Optional[EventFilter] = None
    ) -> Iterator[Event]:
        body = self._filter_dict(filter or EventFilter())
        resp = _request(
            self._url(app_id, "/find"), "POST", json.dumps(body).encode(),
            self._timeout,
        )

        def iterate() -> Iterator[Event]:
            with resp:
                for line in resp:  # http.client decodes the chunked framing
                    line = line.strip()
                    if line:
                        yield Event.from_json_dict(json.loads(line))

        return iterate()

    def _filter_dict(self, flt: EventFilter) -> dict:
        return {
            "start_time": flt.start_time.isoformat() if flt.start_time else None,
            "until_time": flt.until_time.isoformat() if flt.until_time else None,
            "entity_type": flt.entity_type,
            "entity_id": flt.entity_id,
            "event_names": list(flt.event_names) if flt.event_names else None,
            "target_entity_type": flt.target_entity_type,
            "target_entity_id": flt.target_entity_id,
            "has_target_entity_type": flt.has_target_entity_type,
            "has_target_entity_id": flt.has_target_entity_id,
            "limit": flt.limit,
            "reversed": flt.reversed,
        }

    def scan_columnar(self, app_id: int, filter: Optional[EventFilter] = None):
        """Columnar fast path over the wire (same contract as
        ``SqliteEventStore.scan_columnar``); the server delegates to the
        backing store's native columnar scan."""
        import numpy as np

        body = json.dumps(self._filter_dict(filter or EventFilter())).encode()
        with _request(
            self._url(app_id, "/scan_columnar"), "POST", body, self._timeout
        ) as r:
            cols = _json(r)
        cols["event_time_ms"] = np.asarray(cols["event_time_ms"], dtype=np.int64)
        return cols

    def write(self, events, app_id: int) -> None:
        body = json.dumps([e.to_json_dict() for e in events]).encode()
        with _request(self._url(app_id, "/batch"), "POST", body, self._timeout):
            pass

    def write_new(self, events, app_id: int) -> None:
        """Freshness contract forwarded to the server so the backing store
        can take its guaranteed-new batch path."""
        body = json.dumps([e.to_json_dict() for e in events]).encode()
        with _request(
            self._url(app_id, "/batch?fresh=1"), "POST", body, self._timeout
        ):
            pass


class _RemoteRPC:
    """One metadata RPC method bound to a URL."""

    def __init__(self, base: str, method: str, timeout: float):
        self._base, self._method, self._timeout = base, method, timeout

    def __call__(self, *args):
        body = json.dumps(
            {"method": self._method, "args": [encode(a) for a in args]}
        ).encode()
        with _request(f"{self._base}/metadata/rpc", "POST", body, self._timeout) as r:
            return decode(_json(r)["result"])


class RemoteMetadataStore:
    """Duck-typed ``MetadataStore`` forwarding every DAO method over RPC.

    The method list is pinned server-side (``METADATA_RPC_METHODS``); here
    each becomes a bound callable, so call sites are oblivious to the wire.
    """

    def __init__(self, base_url: str, timeout: float = 60.0):
        base = base_url.rstrip("/")
        for method in METADATA_RPC_METHODS:
            setattr(self, method, _RemoteRPC(base, method, timeout))

    def close(self) -> None:
        pass


class RemoteModelStore(ModelStore):
    def __init__(self, base_url: str, timeout: float = 60.0):
        self._base = base_url.rstrip("/")
        self._timeout = timeout

    def insert(self, model: Model) -> None:
        with _request(
            f"{self._base}/models/{model.id}", "PUT", model.models, self._timeout
        ):
            pass

    def get(self, id: str) -> Optional[Model]:
        try:
            with _request(f"{self._base}/models/{id}", timeout=self._timeout) as r:
                return Model(id=id, models=r.read())
        except RemoteStorageError as exc:
            if exc.code == 404:
                return None
            raise

    def delete(self, id: str) -> None:
        with _request(f"{self._base}/models/{id}", "DELETE", timeout=self._timeout):
            pass


def _base_url(conf: SourceConf) -> str:
    host = conf.get("host", "127.0.0.1")
    port = int(conf.get("port", DEFAULT_PORT))
    return f"http://{host}:{port}"


register_backend(
    BackendFamily(
        name="remote",
        events=lambda c: RemoteEventStore(_base_url(c)),
        metadata=lambda c: RemoteMetadataStore(_base_url(c)),
        models=lambda c: RemoteModelStore(_base_url(c)),
    )
)
