"""Remote storage backend family (type ``remote``).

Client half of the server-mode storage pair (``storage/storage_server.py``)
— the rebuild's analogue of the reference's networked backends, where every
store is a client to a storage service (HBase ``StorageClient`` holding an
HConnection, ES ``StorageClient`` holding a ``TransportClient``;
``data/src/main/scala/io/prediction/data/storage/hbase/StorageClient.scala``,
``elasticsearch/StorageClient.scala``). Source conf keys::

    PIO_STORAGE_SOURCES_<NAME>_TYPE=remote
    PIO_STORAGE_SOURCES_<NAME>_HOST=10.0.0.2     (default 127.0.0.1)
    PIO_STORAGE_SOURCES_<NAME>_PORT=7079
    PIO_STORAGE_SOURCES_<NAME>_NODES=primary:7079,replica1:7079  (HA)
    PIO_STORAGE_SOURCES_<NAME>_URL=pio+ha://primary:7079,replica1:7079

This module self-registers the family on import: the registry's
``resolve_backend`` imports ``predictionio_tpu.storage.remote`` the first
time it meets ``type=remote`` — nothing in ``registry.py`` names this
backend (the pluggability contract, ``Storage.scala:176-217``).

Event scans stream as ndjson, so ``find`` over a huge app yields in bounded
memory on both sides.

Resilience (``docs/robustness.md``): every request honors the ambient
request :class:`~predictionio_tpu.utils.resilience.Deadline` (socket
timeout capped to the remaining budget; the budget is forwarded via the
``X-PIO-Deadline-Ms`` header so the server can short-circuit too), each
storage netloc gets a :class:`CircuitBreaker` (``PIO_BREAKER_*`` env) so
a dead storage server fast-fails instead of stacking connect timeouts,
and writes retry only when they are *provably replayable* — an event
carrying an ``event_id`` (e.g. minted from an idempotency key) upserts,
so its POST may take the same one-shot stale-connection retry reads get.
All wire I/O routes through the fault-injection point ``remote.send``
(``predictionio_tpu/testing/faults.py``).

High availability (``docs/storage.md#replication``): a multi-endpoint
URL — ``pio+ha://primary:7079,replica1:7079,...`` — lists the primary
first and warm-standby replicas after. Writes always target the
primary; its ``X-PIO-Seq`` acks feed a process-wide :class:`SeqToken`
shared by all three stores of the endpoint set. Reads go to the primary
until its circuit breaker opens, then fail over to the freshest replica
(ordered by a one-shot ``/replicate/checkpoint`` probe) carrying
``X-PIO-Min-Seq`` = the last acked seq — a replica that has not yet
applied the caller's own writes answers 409 and the next one is tried,
preserving read-your-writes across failover. When the primary is
transport-dead and the write is safe to re-issue (an idempotent upsert,
or the circuit was already open so nothing went out), the write path
additionally *discovers* a promoted replica: it offers the write to the
standbys freshest-first — a standby still answers 409 and is skipped, a
**promoted** one acks and becomes the set's acting primary from then on
(no automatic flip-back: the old primary returning must not split the
write stream).

Partitioned event store (``docs/storage.md#partitioning``): ``;`` in a
``pio+ha://`` URL separates N independent (primary, replicas) sets —
one per keyspace partition, in index order. :class:`RemoteEventStore`
routes every event write to the partition owning its ``(app, entity)``
hash (``storage/partition.py``) through that partition's own circuit
breakers, with a bounded full-jitter retry for replayable writes; a
partition that stays unreachable raises :class:`PartitionUnavailable`
(→ the event server's 503 + Retry-After), so a dead partition sheds
ONLY its keyspace while the other N−1 keep acking. Reads fan out and
merge. Metadata and models are fleet-global, low-rate state: they live
on partition 0's endpoint set (the "meta partition") — only the event
keyspace shards.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
import urllib.parse
from typing import Iterator, Optional

from ..obs.trace import TRACE_HEADER, current_context
from ..testing.faults import fault_point
from ..utils.resilience import (
    CircuitBreaker,
    CircuitOpen,
    Deadline,
    DEADLINE_HEADER,
    RetryPolicy,
    current_deadline,
)
from .backends import BackendFamily, SourceConf, register_backend
from .changefeed import MIN_SEQ_HEADER, SEQ_HEADER
from .event import Event
from .events import EventFilter, EventStore
from .model_store import Model, ModelStore
from .storage_server import (
    DEFAULT_PORT,
    METADATA_READ_METHODS,
    METADATA_RPC_METHODS,
)
from .wire import decode, encode


class RemoteStorageError(Exception):
    """Transport or server-side failure, with the server's message.
    ``code`` is the HTTP status, or ``None`` for transport errors."""

    def __init__(self, message: str, code: Optional[int] = None):
        super().__init__(message)
        self.code = code


class PartitionUnavailable(RemoteStorageError):
    """One (or more) event-store partitions cannot take the operation:
    primary transport-dead, breaker open, and no promoted standby found
    after the bounded retry schedule. Only the listed partitions'
    keyspace is affected — the caller (the event server's ingest path)
    sheds exactly those keys with 503 + ``retry_after_s`` while every
    other partition keeps acking (``docs/robustness.md``)."""

    def __init__(
        self,
        message: str,
        partitions,
        retry_after_s: float = 1.0,
    ):
        super().__init__(message, code=None)
        self.partitions = tuple(partitions)
        self.retry_after_s = retry_after_s


# -- pooled keep-alive transport ---------------------------------------------
#
# Every storage operation used to open a fresh TCP connection (urllib);
# for the multi-host storage plane that is connection setup per metadata
# RPC / event op. Connections are now pooled per (thread, host:port) and
# reused when the previous response was fully drained — a response
# abandoned mid-stream (a partially consumed `find`) discards its
# connection, since leftover body bytes would desync the next request.
# A pooled connection that died while idle (server restart) gets one
# transparent retry on a fresh connection.


class _NetlocPool(threading.local):
    def __init__(self):
        self.conns: dict = {}


_pool = _NetlocPool()


# -- per-netloc circuit breakers ---------------------------------------------
#
# One breaker per storage endpoint, shared by every store/thread talking
# to it: when the storage server is down, the FIRST few operations pay
# the connect timeout and every subsequent one fast-fails with a clear
# "circuit open" error until the cooldown elapses and a probe goes out.
# The clock is module-level-injectable so breaker timing is testable.

_breakers: dict = {}
_breakers_lock = threading.Lock()
_breaker_clock = time.monotonic


def _get_breaker(netloc: str) -> CircuitBreaker:
    with _breakers_lock:
        breaker = _breakers.get(netloc)
        if breaker is None:
            breaker = CircuitBreaker.from_env(netloc, clock=_breaker_clock)
            _breakers[netloc] = breaker
        return breaker


def reset_resilience(clock=None) -> None:
    """Forget all breaker and seq-token state. ``clock`` installs an
    injected breaker clock; ``None`` restores the real monotonic clock
    (so a test that injected a frozen clock cannot leak it into later
    tests). Test hook — production processes never need it."""
    global _breaker_clock
    with _breakers_lock:
        _breakers.clear()
        _breaker_clock = clock if clock is not None else time.monotonic
    with _seq_tokens_lock:
        _seq_tokens.clear()


# -- HA endpoint sets + read-your-writes seq tokens ---------------------------


class SeqToken:
    """Monotonic max of the ``X-PIO-Seq`` acks this process has received
    for one endpoint set — the read-your-writes floor forwarded to
    replicas as ``X-PIO-Min-Seq``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._last = 0

    def note(self, seq: int) -> None:
        with self._lock:
            if seq > self._last:
                self._last = seq

    @property
    def last(self) -> int:
        with self._lock:
            return self._last


#: one shared token per endpoint set, so the event/metadata/model stores
#: of one storage plane see each other's write acks (write a model, read
#: it back through a replica — still your own write)
_seq_tokens: dict = {}
_seq_tokens_lock = threading.Lock()


def _get_seq_token(key: str) -> SeqToken:
    with _seq_tokens_lock:
        token = _seq_tokens.get(key)
        if token is None:
            token = SeqToken()
            _seq_tokens[key] = token
        return token


def _split_endpoints(base_url: str) -> list:
    """``pio+ha://a:1,b:2`` → ``["http://a:1", "http://b:2"]``; any other
    URL is a single-endpoint set."""
    base_url = base_url.strip()
    if not base_url.startswith("pio+ha://"):
        return [base_url.rstrip("/")]
    urls = []
    for part in base_url[len("pio+ha://"):].split(","):
        part = part.strip().rstrip("/")
        if part:
            urls.append(part if "://" in part else f"http://{part}")
    if not urls:
        raise RemoteStorageError(f"no endpoints in HA URL {base_url!r}")
    return urls


class _HAEndpoints:
    """One store's view of a (primary, replicas) endpoint set."""

    def __init__(self, base_url: str):
        urls = _split_endpoints(base_url)
        self.primary = urls[0]
        self.replicas = tuple(urls[1:])
        self.token = _get_seq_token("|".join(urls))
        self._order_lock = threading.Lock()
        self._order = None  # freshness-sorted replicas, cached per outage
        #: a promoted standby discovered by the write path; once set,
        #: writes go there — NO automatic flip-back when the old primary
        #: reappears (two nodes accepting writes would split the stream)
        self._acting_primary: Optional[str] = None

    def write_url(self) -> str:
        with self._order_lock:
            return self._acting_primary or self.primary

    def set_acting_primary(self, url: str) -> None:
        with self._order_lock:
            if url != self.primary:
                self._acting_primary = url

    def note_response(self, resp) -> None:
        seq = resp.getheader(SEQ_HEADER)
        if seq is not None:
            try:
                self.token.note(int(seq))
            except ValueError:
                pass

    def clear_order(self) -> None:
        with self._order_lock:
            self._order = None

    def replica_order(self, timeout: float) -> tuple:
        """Replicas sorted freshest-first by a one-shot
        ``/replicate/checkpoint`` probe, cached until the primary answers
        again (one probe round per outage, not per read)."""
        with self._order_lock:
            if self._order is not None:
                return self._order
        seqs = []
        for url in self.replicas:
            try:
                with _request(
                    f"{url}/replicate/checkpoint",
                    timeout=min(timeout, 5.0),
                ) as resp:
                    seqs.append((int(_json(resp).get("seq", -1)), url))
            except (RemoteStorageError, ValueError):
                seqs.append((-1, url))
        order = tuple(url for _, url in sorted(seqs, key=lambda t: -t[0]))
        with self._order_lock:
            self._order = order
        return order


def _ha_write(
    endpoints: _HAEndpoints,
    path: str,
    method: str = "POST",
    body: Optional[bytes] = None,
    timeout: float = 60.0,
    idempotent: Optional[bool] = None,
):
    """Mutations target the set's write endpoint (the configured primary,
    or a previously discovered promoted standby); a successful ack's seq
    feeds the shared token.

    Promoted-standby discovery: when the write target is transport-dead
    AND re-issuing the request cannot double-apply — it is an idempotent
    upsert, or the circuit was already open so no bytes ever went out —
    the write is offered to the standbys freshest-first. A standby that
    is still a replica answers 409 (it is skipped and the set keeps
    shedding); a **promoted** one acks, becomes the acting primary, and
    the outage is over for this keyspace. A non-replayable write after
    an in-flight transport failure still raises immediately: the dead
    primary may have executed it."""
    target = endpoints.write_url()
    try:
        resp = _request(
            target + path, method, body, timeout, idempotent=idempotent
        )
        endpoints.note_response(resp)
        return resp
    except RemoteStorageError as exc:
        if exc.code is not None or not endpoints.replicas:
            raise  # the server answered (409/500/...), or nowhere to go
        effective_idempotent = (
            idempotent if idempotent is not None
            else method in ("GET", "DELETE")
        )
        if not (
            getattr(exc, "circuit_open", False) or effective_idempotent
        ):
            raise  # may have executed server-side: a replay could double-apply
        for candidate in endpoints.replica_order(timeout):
            if candidate == target:
                continue
            try:
                resp = _request(
                    candidate + path, method, body, timeout,
                    idempotent=idempotent,
                )
            except RemoteStorageError:
                # 409 = still a replica (writes have no home yet); any
                # transport error = that standby is down too — next
                continue
            endpoints.note_response(resp)
            endpoints.set_acting_primary(candidate)
            return resp
        # no promoted standby: the set is write-dead. Re-raise the
        # ORIGINAL outage (not a candidate's 409) — the caller's shed
        # path keys on "transport-dead", and a 409 here would read as
        # "the server answered", hiding the outage.
        raise exc


def _ha_read(
    endpoints: _HAEndpoints,
    path: str,
    method: str = "GET",
    body: Optional[bytes] = None,
    timeout: float = 60.0,
    idempotent: bool = True,
):
    """Reads prefer the primary (or the discovered acting primary after
    a write failover); once its breaker is open (the endpoint is
    known-dead, PR 2 semantics) they fail over to the freshest replica
    carrying the read-your-writes floor. A single transient primary
    failure below the breaker threshold still raises — failover is an
    outage response, not a retry policy."""
    preferred = endpoints.write_url()
    if not endpoints.replicas:
        return _request(
            preferred + path, method, body, timeout,
            idempotent=idempotent,
        )
    try:
        resp = _request(
            preferred + path, method, body, timeout,
            idempotent=idempotent,
        )
        endpoints.clear_order()  # healthy again: next outage re-probes
        return resp
    except RemoteStorageError as exc:
        if exc.code is not None:
            raise  # the server answered; an HTTP error is not an outage
        breaker = _get_breaker(_netloc(preferred))
        if not getattr(exc, "circuit_open", False) and (
            breaker.state == CircuitBreaker.CLOSED
        ):
            raise
        last_exc = exc
    min_seq = endpoints.token.last
    headers = {MIN_SEQ_HEADER: str(min_seq)} if min_seq else None
    for replica_url in endpoints.replica_order(timeout):
        try:
            return _request(
                replica_url + path, method, body, timeout,
                idempotent=idempotent, headers=headers,
            )
        except RemoteStorageError as exc:
            last_exc = exc  # behind (409), down, or breaker-open: next
    raise last_exc


def _netloc(url: str) -> str:
    parsed = urllib.parse.urlsplit(url)
    return f"{parsed.scheme}://{parsed.netloc}"


def _conn_is_dead(conn) -> bool:
    """Liveness probe for an idle pooled connection: with no request in
    flight the socket must have nothing to read, so readability means EOF
    (server closed while idle) or protocol garbage — either way, dead."""
    sock = getattr(conn, "sock", None)
    if sock is None:
        return True
    try:
        import select

        readable, _, _ = select.select([sock], [], [], 0)
        return bool(readable)
    except (OSError, ValueError):
        return True


def _return_conn(netloc: str, conn) -> None:
    """Pool a reusable connection; close any displaced one (possible when
    an RPC ran while a streaming response held the slot's connection)."""
    old = _pool.conns.get(netloc)
    if old is not None and old is not conn:
        try:
            old.close()
        except Exception:
            pass
    _pool.conns[netloc] = conn


class _PooledResponse:
    """Proxy over ``http.client.HTTPResponse`` that returns the connection
    to the per-thread pool when the body was fully read."""

    def __init__(self, resp, conn, netloc: str):
        self._resp = resp
        self._conn = conn
        self._netloc = netloc

    # the access patterns used by this module's callers
    def read(self, *a):
        return self._resp.read(*a)

    def getheader(self, name, default=None):
        return self._resp.getheader(name, default)

    def __iter__(self):
        return iter(self._resp)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self) -> None:
        conn, self._conn = self._conn, None
        if conn is None:
            return
        resp = self._resp
        if not resp.isclosed():
            # Callers that only wanted the status (`with _request(...):
            # pass` on write paths) leave a small JSON body unread —
            # drain a bounded amount so those connections still pool;
            # genuinely large/streaming leftovers get discarded.
            try:
                resp.read(1 << 16)
            except Exception:
                conn.close()
                return
        if resp.isclosed() and not getattr(resp, "will_close", False):
            _return_conn(self._netloc, conn)
        else:
            conn.close()

    def __del__(self):  # a response dropped without close(): free the fd
        conn = self._conn
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass


def _request(
    url: str,
    method: str = "GET",
    body: Optional[bytes] = None,
    timeout: float = 60.0,
    idempotent: Optional[bool] = None,
    deadline: Optional[Deadline] = None,
    headers: Optional[dict] = None,
):
    """Traced front of :func:`_request_impl` (``docs/observability.md``):
    when the calling thread carries an ambient span context (it is
    serving a traced request), the trace id is forwarded in
    ``X-PIO-Trace`` — so the storage server's admission span joins the
    same trace — and a client span is recorded around the call. The
    span covers up to response headers; a streamed body (``find``)
    continues past it. Replica failover probes and failed-over reads go
    through here too, so an outage's probe round is visible in the
    trace."""
    ctx = current_context()
    if ctx is None:
        return _request_impl(
            url, method, body, timeout, idempotent, deadline, headers
        )
    headers = dict(headers or {})
    headers.setdefault(TRACE_HEADER, ctx.trace_id)
    with ctx.tracer.span(
        f"storage.{method}", tags={"url": url}, parent=ctx
    ):
        return _request_impl(
            url, method, body, timeout, idempotent, deadline, headers
        )


def _request_impl(
    url: str,
    method: str = "GET",
    body: Optional[bytes] = None,
    timeout: float = 60.0,
    idempotent: Optional[bool] = None,
    deadline: Optional[Deadline] = None,
    headers: Optional[dict] = None,
):
    """``idempotent`` enables the one-shot stale-connection retry and
    unconditional pool reuse. Default: GET/DELETE only. POST call sites
    that are semantically reads (find, columnar scans) or natural upserts
    (init, model put, keyed event inserts) opt in.

    Non-idempotent requests (unkeyed event inserts, bulk writes) get NO
    retry — a request the server executed before dying would be applied
    twice. They may still borrow a pooled connection, but only after a
    liveness probe (``_conn_is_dead``): a socket the server closed while
    idle shows EOF and is discarded for a fresh connection, so the common
    stale-keep-alive failure can't hit a write, while high-rate writers
    keep keep-alive (no per-event TCP handshake). The probe-to-send race
    window — server closes in the microseconds between — surfaces as a
    loud RemoteStorageError, never a silent replay.

    ``deadline`` (default: the ambient request deadline, if any) caps the
    socket timeout to the remaining budget and is forwarded in the
    ``X-PIO-Deadline-Ms`` header; an already-expired deadline raises
    before any socket work. The per-netloc circuit breaker fast-fails
    every call while the endpoint is known-dead (see module docstring)."""
    parsed = urllib.parse.urlsplit(url)
    if parsed.scheme not in ("http", "https"):
        raise RemoteStorageError(f"unsupported URL scheme in {url!r}")
    conn_cls = (
        http.client.HTTPSConnection
        if parsed.scheme == "https"
        else http.client.HTTPConnection
    )
    default_port = 443 if parsed.scheme == "https" else DEFAULT_PORT
    if idempotent is None:
        idempotent = method in ("GET", "DELETE")
    netloc = f"{parsed.scheme}://{parsed.netloc}"
    path = parsed.path + (f"?{parsed.query}" if parsed.query else "")
    headers = dict(headers or {})
    if body is not None:
        headers.setdefault("Content-Type", "application/json")
    if deadline is None:
        deadline = current_deadline()
    breaker = _get_breaker(netloc)
    try:
        breaker.before_call()
    except CircuitOpen as exc:
        err = RemoteStorageError(f"{method} {url} not attempted: {exc}")
        err.circuit_open = True  # the HA read path keys failover on this
        raise err from exc
    base_timeout = timeout
    for attempt in (0, 1):
        # Deadline accounting PER ATTEMPT: the stale-keep-alive retry
        # must re-check the budget, re-cap its socket timeout to what is
        # actually left, and forward the CURRENT remaining ms — not the
        # figures computed before attempt 0 burned part of the budget.
        if deadline is not None:
            deadline.check(f"{method} {url}")
            timeout = deadline.cap_timeout(base_timeout)
            headers[DEADLINE_HEADER] = deadline.header_value()
        conn = _pool.conns.pop(netloc, None)
        if conn is not None and not idempotent and _conn_is_dead(conn):
            # a write must not meet a stale socket (no retry is allowed);
            # reads keep the cheap path — their stale retry is safe
            try:
                conn.close()
            except Exception:
                pass
            conn = None
        fresh = conn is None
        if fresh:
            conn = conn_cls(
                parsed.hostname, parsed.port or default_port, timeout=timeout
            )
        elif conn.sock is not None:
            try:
                conn.sock.settimeout(timeout)  # caller-specific op timeout
            except OSError:  # pooled socket already dead
                conn.close()
                conn = conn_cls(
                    parsed.hostname, parsed.port or default_port,
                    timeout=timeout,
                )
                fresh = True
        try:
            # fault-injection boundary: an injected refuse/close/reset
            # takes exactly the except paths a real one would
            fault_point(
                "remote.send",
                method=method,
                url=url,
                fresh=fresh,
                idempotent=idempotent,
            )
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
        except Exception as exc:
            try:
                conn.close()
            except Exception:
                pass
            # Retry ONLY the stale-keep-alive signature: a pooled
            # connection the server closed while idle fails with a
            # connection-level error. Timeouts and fresh-connection
            # failures must NOT retry — the request may have executed
            # server-side, and unkeyed storage writes are not idempotent.
            stale_reuse = (
                not fresh
                and idempotent
                and isinstance(
                    exc,
                    (
                        BrokenPipeError,
                        ConnectionResetError,
                        http.client.RemoteDisconnected,
                    ),
                )
            )
            if not stale_reuse:
                breaker.record_failure()
                raise RemoteStorageError(
                    f"{method} {url} unreachable: {exc}"
                ) from exc
            continue
        # a response of ANY status proves the endpoint is alive: HTTP
        # errors are the server talking, not the dependency being down
        breaker.record_success()
        if resp.status >= 400:
            detail = resp.read().decode("utf-8", "replace")[:500]
            if resp.isclosed() and not getattr(resp, "will_close", False):
                _return_conn(netloc, conn)
            else:
                conn.close()
            raise RemoteStorageError(
                f"{method} {url} → HTTP {resp.status}: {detail}",
                code=resp.status,
            )
        return _PooledResponse(resp, conn, netloc)
    raise AssertionError("unreachable")  # pragma: no cover


def _json(resp) -> dict:
    return json.loads(resp.read())


#: bounded full-jitter retry schedule for replayable writes against one
#: partition (docs/robustness.md): 3 total tries, 50 ms base doubling to
#: a 0.5 s cap — enough to ride out a primary restart's socket blip,
#: bounded enough that a dead partition sheds within ~1 s. Deadline-aware
#: (no retry is attempted once the ambient budget cannot cover its
#: backoff). Env-tunable attempts for drills.
PARTITION_RETRY_ATTEMPTS_ENV = "PIO_PARTITION_RETRY_ATTEMPTS"


def _partition_retry_policy(sleep=time.sleep) -> RetryPolicy:
    import os

    attempts = 3
    raw = os.environ.get(PARTITION_RETRY_ATTEMPTS_ENV)
    if raw:
        try:
            attempts = max(1, int(raw))
        except ValueError:
            pass
    return RetryPolicy(
        attempts=attempts, base_delay_s=0.05, max_delay_s=0.5,
        retry_on=(RemoteStorageError,), sleep=sleep,
    )


class RemoteEventStore(EventStore):
    """``EventStore`` over the storage server's /events routes.

    A partitioned URL (``;``-separated endpoint sets, index order —
    module docstring) makes this the fan-out client of the partitioned
    write path: writes route to the owning partition, reads fan out and
    merge, and per-partition failures surface as
    :class:`PartitionUnavailable` so only that keyspace sheds."""

    def __init__(self, base_url: str, timeout: float = 60.0):
        from .partition import split_partition_sets

        # 60 s default mirrors the reference LEvents op timeout
        # (LEvents.scala:35).
        self._parts = [
            _HAEndpoints(u) for u in split_partition_sets(base_url)
        ]
        self._ep = self._parts[0]
        self._timeout = timeout
        self._retry = _partition_retry_policy()

    @property
    def partition_count(self) -> int:
        return len(self._parts)

    def partition_for(self, app_id: int, entity_id: str) -> int:
        """The owning partition of one (app, entity) key — exposed so
        the event server's batch path can group a mixed batch and shed
        per keyspace (docs/storage.md#partitioning)."""
        from .partition import partition_for_event

        return partition_for_event(len(self._parts), int(app_id), entity_id)

    def _ep_for(self, app_id: int, entity_id: str):
        idx = self.partition_for(app_id, entity_id)
        return idx, self._parts[idx]

    def _partition_call(self, idx: int, fn, retryable: bool):
        """Run one partition-bound operation under the bounded jittered
        retry (replayable ops only), converting a transport-dead
        partition into :class:`PartitionUnavailable` — HTTP-status
        errors (the server talking) pass through untouched."""

        def transient(exc: BaseException) -> bool:
            return (
                isinstance(exc, RemoteStorageError)
                and exc.code is None
                and not getattr(exc, "circuit_open", False)
            )

        try:
            if retryable:
                return self._retry.call(
                    fn, should_retry=transient, deadline=current_deadline()
                )
            return fn()
        except RemoteStorageError as exc:
            if exc.code is not None:
                raise
            raise PartitionUnavailable(
                f"event-store partition {idx} of {len(self._parts)} "
                f"unavailable: {exc}",
                partitions=(idx,),
            ) from exc

    def _path(self, app_id: int, suffix: str = "") -> str:
        return f"/events/{app_id}{suffix}"

    def _fan_all(self, fn, retryable: bool):
        """Run one op against EVERY partition (app lifecycle, bulk
        groups). All partitions are attempted — a dead one must not
        starve the rest — then the failures raise together."""
        results = []
        failed: list = []
        last: Optional[RemoteStorageError] = None
        for idx in range(len(self._parts)):
            try:
                results.append(self._partition_call(
                    idx, lambda i=idx: fn(i, self._parts[i]), retryable
                ))
            except PartitionUnavailable as exc:
                failed.extend(exc.partitions)
                last = exc
        if failed:
            raise PartitionUnavailable(
                f"event-store partition(s) {sorted(failed)} of "
                f"{len(self._parts)} unavailable: {last}",
                partitions=sorted(failed),
            ) from last
        return results

    def init(self, app_id: int) -> bool:
        def one(_idx, ep) -> bool:
            with _ha_write(ep, self._path(app_id, "/init"), "POST", b"{}",
                           self._timeout, idempotent=True) as r:
                return bool(_json(r)["ok"])

        return all(self._fan_all(one, retryable=True))

    def remove(self, app_id: int) -> bool:
        def one(_idx, ep) -> bool:
            with _ha_write(ep, self._path(app_id, "/remove"), "POST", b"{}",
                           self._timeout, idempotent=True) as r:
                return bool(_json(r)["ok"])

        return all(self._fan_all(one, retryable=True))

    def insert(self, event: Event, app_id: int) -> str:
        body = json.dumps(event.to_json_dict()).encode()
        # An event that already carries its id (client-assigned, or
        # minted from an idempotencyKey upstream) is an UPSERT on the
        # server: replaying it lands on itself, so the POST may take the
        # one-shot stale-connection retry AND the partition retry
        # schedule. Unkeyed inserts keep NO retry — a replay would
        # double-insert.
        idempotent = event.event_id is not None
        idx, ep = self._ep_for(app_id, event.entity_id)

        def send() -> str:
            with _ha_write(
                ep, self._path(app_id), "POST", body, self._timeout,
                idempotent=idempotent,
            ) as r:
                return _json(r)["eventId"]

        return self._partition_call(idx, send, retryable=idempotent)

    def get(self, event_id: str, app_id: int) -> Optional[Event]:
        # an event id does not carry its entity key: point reads probe
        # every partition (cheap: N is small, misses are indexed 404s)
        last: Optional[RemoteStorageError] = None
        for ep in self._parts:
            try:
                with _ha_read(
                    ep, self._path(app_id, f"/{event_id}"),
                    timeout=self._timeout,
                ) as r:
                    return Event.from_json_dict(_json(r))
            except RemoteStorageError as exc:
                if exc.code == 404:
                    continue
                last = exc
        if last is not None:
            # a miss everywhere with a partition unreachable is NOT a
            # clean "absent" — the event may live on the dead partition
            raise last
        return None

    def delete(self, event_id: str, app_id: int) -> bool:
        def one(_idx, ep) -> bool:
            with _ha_write(
                ep, self._path(app_id, f"/{event_id}"), "DELETE",
                timeout=self._timeout,
            ) as r:
                return bool(_json(r)["found"])

        # attempt-all-then-raise (the _fan_all discipline): a dead
        # partition must not stop the delete from landing everywhere
        # else, and the raised error names every failed partition
        return any(self._fan_all(one, retryable=True))

    def find(
        self, app_id: int, filter: Optional[EventFilter] = None
    ) -> Iterator[Event]:
        flt = filter or EventFilter()
        body = json.dumps(self._filter_dict(flt)).encode()
        if len(self._parts) == 1:
            resp = _ha_read(
                self._ep, self._path(app_id, "/find"), "POST",
                body, self._timeout,  # pure read
            )

            def iterate() -> Iterator[Event]:
                with resp:
                    for line in resp:  # http.client decodes the framing
                        line = line.strip()
                        if line:
                            yield Event.from_json_dict(json.loads(line))

            return iterate()
        # Partitioned scan: every partition streams its own time-ordered
        # slice; a lazy k-way merge re-establishes the global
        # (event_time, event_id) order the single-store contract
        # promises. A dead partition fails the scan LOUDLY (after read
        # failover to its replicas) — a silently truncated training scan
        # is worse than an error, same principle as the fleet's dead
        # shard (docs/fleet.md).
        responses: list = []

        def close_all() -> None:
            for resp in responses:
                try:
                    resp.close()
                except Exception:
                    pass

        try:
            for ep in self._parts:
                responses.append(
                    _ha_read(
                        ep, self._path(app_id, "/find"), "POST", body,
                        self._timeout,
                    )
                )
        except Exception:
            # a later partition failed to open: the already-open
            # streams must not linger with unread bodies poisoning
            # their pooled connections
            close_all()
            raise

        def stream(resp) -> Iterator[Event]:
            with resp:
                for line in resp:
                    line = line.strip()
                    if line:
                        yield Event.from_json_dict(json.loads(line))

        def merged() -> Iterator[Event]:
            import heapq

            def key(e: Event):
                return (e.event_time, e.event_id or "")

            try:
                produced = 0
                limit = flt.limit  # None or <0 = unlimited (EventFilter)
                bounded = limit is not None and limit >= 0
                for event in heapq.merge(
                    *(stream(r) for r in responses),
                    key=key, reverse=bool(flt.reversed),
                ):
                    if bounded and produced >= limit:
                        return
                    yield event
                    produced += 1
            finally:
                # an abandoned/limited merge must release the N-1
                # still-open streams deterministically, not at GC time
                close_all()

        return merged()

    def _filter_dict(self, flt: EventFilter) -> dict:
        return {
            "start_time": flt.start_time.isoformat() if flt.start_time else None,
            "until_time": flt.until_time.isoformat() if flt.until_time else None,
            "entity_type": flt.entity_type,
            "entity_id": flt.entity_id,
            "event_names": list(flt.event_names) if flt.event_names else None,
            "target_entity_type": flt.target_entity_type,
            "target_entity_id": flt.target_entity_id,
            "has_target_entity_type": flt.has_target_entity_type,
            "has_target_entity_id": flt.has_target_entity_id,
            "limit": flt.limit,
            "reversed": flt.reversed,
        }

    def scan_columnar(self, app_id: int, filter: Optional[EventFilter] = None):
        """Columnar fast path over the wire (same contract as
        ``SqliteEventStore.scan_columnar``); the server delegates to the
        backing store's native columnar scan. Partitioned: every
        partition's columns concatenate, then one stable argsort on
        ``event_time_ms`` restores the global time order."""
        import numpy as np

        body = json.dumps(self._filter_dict(filter or EventFilter())).encode()
        if len(self._parts) == 1:
            with _ha_read(
                self._ep, self._path(app_id, "/scan_columnar"), "POST",
                body, self._timeout,  # pure read
            ) as r:
                cols = _json(r)
            cols["event_time_ms"] = np.asarray(
                cols["event_time_ms"], dtype=np.int64
            )
            return cols
        merged: Optional[dict] = None
        for ep in self._parts:
            with _ha_read(
                ep, self._path(app_id, "/scan_columnar"), "POST", body,
                self._timeout,
            ) as r:
                cols = _json(r)
            if merged is None:
                merged = {k: list(v) for k, v in cols.items()}
            else:
                for k, v in cols.items():
                    merged[k].extend(v)
        assert merged is not None
        times = np.asarray(merged["event_time_ms"], dtype=np.int64)
        order = np.argsort(times, kind="stable")
        out = {
            k: [v[i] for i in order] for k, v in merged.items()
            if k != "event_time_ms"
        }
        out["event_time_ms"] = times[order]
        return out

    def _write_batch(self, events, app_id: int, fresh: bool) -> None:
        events = list(events)
        suffix = "/batch?fresh=1" if fresh else "/batch"
        if len(self._parts) == 1:
            body = json.dumps([e.to_json_dict() for e in events]).encode()
            with _ha_write(
                self._ep, self._path(app_id, suffix), "POST", body,
                self._timeout,
            ):
                pass
            return
        # Group by owning partition, land every reachable group, then
        # raise ONE PartitionUnavailable naming the dead keyspaces — a
        # mixed batch makes maximum progress, never all-or-nothing
        # behind the slowest partition. No cross-partition buffering:
        # each group is acked (or not) by its own primary's oplog.
        groups: dict = {}
        for event in events:
            idx = self.partition_for(app_id, event.entity_id)
            groups.setdefault(idx, []).append(event)
        failed: list = []
        last: Optional[RemoteStorageError] = None
        for idx in sorted(groups):
            group = groups[idx]
            body = json.dumps([e.to_json_dict() for e in group]).encode()
            # replayable only when every event in the group upserts
            retryable = all(e.event_id is not None for e in group)

            def send(i=idx, b=body) -> None:
                with _ha_write(
                    self._parts[i], self._path(app_id, suffix), "POST", b,
                    self._timeout,
                    idempotent=retryable or None,
                ):
                    pass

            try:
                self._partition_call(idx, send, retryable=retryable)
            except PartitionUnavailable as exc:
                failed.extend(exc.partitions)
                last = exc
        if failed:
            raise PartitionUnavailable(
                f"event batch lost partition(s) {sorted(failed)} of "
                f"{len(self._parts)}: {last}",
                partitions=sorted(failed),
            ) from last

    def write(self, events, app_id: int) -> None:
        self._write_batch(events, app_id, fresh=False)

    def write_new(self, events, app_id: int) -> None:
        """Freshness contract forwarded to the server so the backing store
        can take its guaranteed-new batch path."""
        self._write_batch(events, app_id, fresh=True)

    def partition_status(self, timeout: float = 2.0) -> list:
        """One ``/replication.json``-shaped row per partition, probed
        from this client's view (write endpoint + ``/replicate/
        checkpoint``): the event server's ingest-tier fleet surface and
        ``pio top``'s PARTS column read these rows."""
        rows = []
        n = len(self._parts)
        for idx, ep in enumerate(self._parts):
            url = ep.write_url()
            row = {"partition": idx, "of": n, "endpoint": url, "up": False}
            try:
                with _request(
                    f"{url}/replicate/checkpoint", timeout=timeout
                ) as resp:
                    ck = _json(resp)
                row["up"] = True
                row["seq"] = ck.get("seq")
                row["generation"] = ck.get("generation")
            except (RemoteStorageError, ValueError) as exc:
                if getattr(exc, "code", None) == 404:
                    # alive but changefeed-less: up, just not replicating
                    row["up"] = True
                else:
                    row["error"] = str(exc)[:200]
            rows.append(row)
        return rows


#: Pure-read metadata RPCs: pooled keep-alive + stale retry is safe for
#: these (re-reading is harmless), and replicas may answer them.
#: Mutations (gen_next, inserts, updates, deletes) get no stale retry —
#: gen_next retried twice burns a sequence value, an insert retried
#: twice duplicates a row. The allowlist itself is pinned server-side
#: (``storage_server.METADATA_READ_METHODS``) so the client and the
#: replica write-rejection can never diverge.
_READ_RPC_METHODS = METADATA_READ_METHODS
assert _READ_RPC_METHODS <= METADATA_RPC_METHODS


def _meta_endpoint_set(base_url: str) -> str:
    """Metadata and models are fleet-global, low-rate state: on a
    partitioned URL they live on partition 0's endpoint set (the "meta
    partition") — only the event keyspace shards."""
    from .partition import split_partition_sets

    return split_partition_sets(base_url)[0]


class _RemoteRPC:
    """One metadata RPC method bound to an endpoint set."""

    def __init__(self, endpoints, method: str, timeout: float):
        if isinstance(endpoints, str):  # bare URL accepted for callers
            endpoints = _HAEndpoints(_meta_endpoint_set(endpoints))
        self._ep, self._method, self._timeout = endpoints, method, timeout
        self._read = method in _READ_RPC_METHODS

    def __call__(self, *args):
        body = json.dumps(
            {"method": self._method, "args": [encode(a) for a in args]}
        ).encode()
        if self._read:
            resp = _ha_read(
                self._ep, "/metadata/rpc", "POST", body, self._timeout
            )
        else:
            resp = _ha_write(
                self._ep, "/metadata/rpc", "POST", body, self._timeout,
                idempotent=False,
            )
        with resp as r:
            return decode(_json(r)["result"])


class RemoteMetadataStore:
    """Duck-typed ``MetadataStore`` forwarding every DAO method over RPC.

    The method list is pinned server-side (``METADATA_RPC_METHODS``); here
    each becomes a bound callable, so call sites are oblivious to the wire.
    """

    def __init__(self, base_url: str, timeout: float = 60.0):
        endpoints = _HAEndpoints(_meta_endpoint_set(base_url))
        for method in METADATA_RPC_METHODS:
            setattr(self, method, _RemoteRPC(endpoints, method, timeout))

    def close(self) -> None:
        pass


class RemoteModelStore(ModelStore):
    def __init__(self, base_url: str, timeout: float = 60.0):
        self._ep = _HAEndpoints(_meta_endpoint_set(base_url))
        self._timeout = timeout

    def insert(self, model: Model) -> None:
        # PUT-by-id is a natural upsert: replaying it is safe
        with _ha_write(
            self._ep, f"/models/{model.id}", "PUT", model.models,
            self._timeout, idempotent=True,
        ):
            pass

    def get(self, id: str) -> Optional[Model]:
        try:
            with _ha_read(
                self._ep, f"/models/{id}", timeout=self._timeout
            ) as r:
                return Model(id=id, models=r.read())
        except RemoteStorageError as exc:
            if exc.code == 404:
                return None
            raise

    def delete(self, id: str) -> None:
        with _ha_write(
            self._ep, f"/models/{id}", "DELETE", timeout=self._timeout
        ):
            pass


def _base_url(conf: SourceConf) -> str:
    """Resolve a source conf to a (possibly multi-endpoint, possibly
    partitioned) base URL: ``URL`` verbatim, ``PARTITIONS`` as a
    ``;``-separated partitioned ``pio+ha://`` spec, ``NODES`` as a
    single ``pio+ha://`` set, else HOST/PORT."""
    if conf.get("url"):
        return conf["url"]
    if conf.get("partitions"):
        return f"pio+ha://{conf['partitions']}"
    if conf.get("nodes"):
        return f"pio+ha://{conf['nodes']}"
    host = conf.get("host", "127.0.0.1")
    port = int(conf.get("port", DEFAULT_PORT))
    return f"http://{host}:{port}"


register_backend(
    BackendFamily(
        name="remote",
        events=lambda c: RemoteEventStore(_base_url(c)),
        metadata=lambda c: RemoteMetadataStore(_base_url(c)),
        models=lambda c: RemoteModelStore(_base_url(c)),
    )
)
