"""Remote storage backend family (type ``remote``).

Client half of the server-mode storage pair (``storage/storage_server.py``)
— the rebuild's analogue of the reference's networked backends, where every
store is a client to a storage service (HBase ``StorageClient`` holding an
HConnection, ES ``StorageClient`` holding a ``TransportClient``;
``data/src/main/scala/io/prediction/data/storage/hbase/StorageClient.scala``,
``elasticsearch/StorageClient.scala``). Source conf keys::

    PIO_STORAGE_SOURCES_<NAME>_TYPE=remote
    PIO_STORAGE_SOURCES_<NAME>_HOST=10.0.0.2     (default 127.0.0.1)
    PIO_STORAGE_SOURCES_<NAME>_PORT=7079

This module self-registers the family on import: the registry's
``resolve_backend`` imports ``predictionio_tpu.storage.remote`` the first
time it meets ``type=remote`` — nothing in ``registry.py`` names this
backend (the pluggability contract, ``Storage.scala:176-217``).

Event scans stream as ndjson, so ``find`` over a huge app yields in bounded
memory on both sides.

Resilience (``docs/robustness.md``): every request honors the ambient
request :class:`~predictionio_tpu.utils.resilience.Deadline` (socket
timeout capped to the remaining budget; the budget is forwarded via the
``X-PIO-Deadline-Ms`` header so the server can short-circuit too), each
storage netloc gets a :class:`CircuitBreaker` (``PIO_BREAKER_*`` env) so
a dead storage server fast-fails instead of stacking connect timeouts,
and writes retry only when they are *provably replayable* — an event
carrying an ``event_id`` (e.g. minted from an idempotency key) upserts,
so its POST may take the same one-shot stale-connection retry reads get.
All wire I/O routes through the fault-injection point ``remote.send``
(``predictionio_tpu/testing/faults.py``).
"""

from __future__ import annotations

import http.client
import json
import threading
import time
import urllib.parse
from typing import Iterator, Optional

from ..testing.faults import fault_point
from ..utils.resilience import (
    CircuitBreaker,
    CircuitOpen,
    Deadline,
    DEADLINE_HEADER,
    current_deadline,
)
from .backends import BackendFamily, SourceConf, register_backend
from .event import Event
from .events import EventFilter, EventStore
from .model_store import Model, ModelStore
from .storage_server import DEFAULT_PORT, METADATA_RPC_METHODS
from .wire import decode, encode


class RemoteStorageError(Exception):
    """Transport or server-side failure, with the server's message.
    ``code`` is the HTTP status, or ``None`` for transport errors."""

    def __init__(self, message: str, code: Optional[int] = None):
        super().__init__(message)
        self.code = code


# -- pooled keep-alive transport ---------------------------------------------
#
# Every storage operation used to open a fresh TCP connection (urllib);
# for the multi-host storage plane that is connection setup per metadata
# RPC / event op. Connections are now pooled per (thread, host:port) and
# reused when the previous response was fully drained — a response
# abandoned mid-stream (a partially consumed `find`) discards its
# connection, since leftover body bytes would desync the next request.
# A pooled connection that died while idle (server restart) gets one
# transparent retry on a fresh connection.


class _NetlocPool(threading.local):
    def __init__(self):
        self.conns: dict = {}


_pool = _NetlocPool()


# -- per-netloc circuit breakers ---------------------------------------------
#
# One breaker per storage endpoint, shared by every store/thread talking
# to it: when the storage server is down, the FIRST few operations pay
# the connect timeout and every subsequent one fast-fails with a clear
# "circuit open" error until the cooldown elapses and a probe goes out.
# The clock is module-level-injectable so breaker timing is testable.

_breakers: dict = {}
_breakers_lock = threading.Lock()
_breaker_clock = time.monotonic


def _get_breaker(netloc: str) -> CircuitBreaker:
    with _breakers_lock:
        breaker = _breakers.get(netloc)
        if breaker is None:
            breaker = CircuitBreaker.from_env(netloc, clock=_breaker_clock)
            _breakers[netloc] = breaker
        return breaker


def reset_resilience(clock=None) -> None:
    """Forget all breaker state (and optionally swap the breaker clock).
    Test hook — production processes never need it."""
    global _breaker_clock
    with _breakers_lock:
        _breakers.clear()
        if clock is not None:
            _breaker_clock = clock


def _conn_is_dead(conn) -> bool:
    """Liveness probe for an idle pooled connection: with no request in
    flight the socket must have nothing to read, so readability means EOF
    (server closed while idle) or protocol garbage — either way, dead."""
    sock = getattr(conn, "sock", None)
    if sock is None:
        return True
    try:
        import select

        readable, _, _ = select.select([sock], [], [], 0)
        return bool(readable)
    except (OSError, ValueError):
        return True


def _return_conn(netloc: str, conn) -> None:
    """Pool a reusable connection; close any displaced one (possible when
    an RPC ran while a streaming response held the slot's connection)."""
    old = _pool.conns.get(netloc)
    if old is not None and old is not conn:
        try:
            old.close()
        except Exception:
            pass
    _pool.conns[netloc] = conn


class _PooledResponse:
    """Proxy over ``http.client.HTTPResponse`` that returns the connection
    to the per-thread pool when the body was fully read."""

    def __init__(self, resp, conn, netloc: str):
        self._resp = resp
        self._conn = conn
        self._netloc = netloc

    # the three access patterns used by this module's callers
    def read(self, *a):
        return self._resp.read(*a)

    def __iter__(self):
        return iter(self._resp)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self) -> None:
        conn, self._conn = self._conn, None
        if conn is None:
            return
        resp = self._resp
        if not resp.isclosed():
            # Callers that only wanted the status (`with _request(...):
            # pass` on write paths) leave a small JSON body unread —
            # drain a bounded amount so those connections still pool;
            # genuinely large/streaming leftovers get discarded.
            try:
                resp.read(1 << 16)
            except Exception:
                conn.close()
                return
        if resp.isclosed() and not getattr(resp, "will_close", False):
            _return_conn(self._netloc, conn)
        else:
            conn.close()

    def __del__(self):  # a response dropped without close(): free the fd
        conn = self._conn
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass


def _request(
    url: str,
    method: str = "GET",
    body: Optional[bytes] = None,
    timeout: float = 60.0,
    idempotent: Optional[bool] = None,
    deadline: Optional[Deadline] = None,
):
    """``idempotent`` enables the one-shot stale-connection retry and
    unconditional pool reuse. Default: GET/DELETE only. POST call sites
    that are semantically reads (find, columnar scans) or natural upserts
    (init, model put, keyed event inserts) opt in.

    Non-idempotent requests (unkeyed event inserts, bulk writes) get NO
    retry — a request the server executed before dying would be applied
    twice. They may still borrow a pooled connection, but only after a
    liveness probe (``_conn_is_dead``): a socket the server closed while
    idle shows EOF and is discarded for a fresh connection, so the common
    stale-keep-alive failure can't hit a write, while high-rate writers
    keep keep-alive (no per-event TCP handshake). The probe-to-send race
    window — server closes in the microseconds between — surfaces as a
    loud RemoteStorageError, never a silent replay.

    ``deadline`` (default: the ambient request deadline, if any) caps the
    socket timeout to the remaining budget and is forwarded in the
    ``X-PIO-Deadline-Ms`` header; an already-expired deadline raises
    before any socket work. The per-netloc circuit breaker fast-fails
    every call while the endpoint is known-dead (see module docstring)."""
    parsed = urllib.parse.urlsplit(url)
    if parsed.scheme not in ("http", "https"):
        raise RemoteStorageError(f"unsupported URL scheme in {url!r}")
    conn_cls = (
        http.client.HTTPSConnection
        if parsed.scheme == "https"
        else http.client.HTTPConnection
    )
    default_port = 443 if parsed.scheme == "https" else DEFAULT_PORT
    if idempotent is None:
        idempotent = method in ("GET", "DELETE")
    netloc = f"{parsed.scheme}://{parsed.netloc}"
    path = parsed.path + (f"?{parsed.query}" if parsed.query else "")
    headers = {"Content-Type": "application/json"} if body is not None else {}
    if deadline is None:
        deadline = current_deadline()
    breaker = _get_breaker(netloc)
    try:
        breaker.before_call()
    except CircuitOpen as exc:
        raise RemoteStorageError(
            f"{method} {url} not attempted: {exc}"
        ) from exc
    base_timeout = timeout
    for attempt in (0, 1):
        # Deadline accounting PER ATTEMPT: the stale-keep-alive retry
        # must re-check the budget, re-cap its socket timeout to what is
        # actually left, and forward the CURRENT remaining ms — not the
        # figures computed before attempt 0 burned part of the budget.
        if deadline is not None:
            deadline.check(f"{method} {url}")
            timeout = deadline.cap_timeout(base_timeout)
            headers[DEADLINE_HEADER] = deadline.header_value()
        conn = _pool.conns.pop(netloc, None)
        if conn is not None and not idempotent and _conn_is_dead(conn):
            # a write must not meet a stale socket (no retry is allowed);
            # reads keep the cheap path — their stale retry is safe
            try:
                conn.close()
            except Exception:
                pass
            conn = None
        fresh = conn is None
        if fresh:
            conn = conn_cls(
                parsed.hostname, parsed.port or default_port, timeout=timeout
            )
        elif conn.sock is not None:
            try:
                conn.sock.settimeout(timeout)  # caller-specific op timeout
            except OSError:  # pooled socket already dead
                conn.close()
                conn = conn_cls(
                    parsed.hostname, parsed.port or default_port,
                    timeout=timeout,
                )
                fresh = True
        try:
            # fault-injection boundary: an injected refuse/close/reset
            # takes exactly the except paths a real one would
            fault_point(
                "remote.send",
                method=method,
                url=url,
                fresh=fresh,
                idempotent=idempotent,
            )
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
        except Exception as exc:
            try:
                conn.close()
            except Exception:
                pass
            # Retry ONLY the stale-keep-alive signature: a pooled
            # connection the server closed while idle fails with a
            # connection-level error. Timeouts and fresh-connection
            # failures must NOT retry — the request may have executed
            # server-side, and unkeyed storage writes are not idempotent.
            stale_reuse = (
                not fresh
                and idempotent
                and isinstance(
                    exc,
                    (
                        BrokenPipeError,
                        ConnectionResetError,
                        http.client.RemoteDisconnected,
                    ),
                )
            )
            if not stale_reuse:
                breaker.record_failure()
                raise RemoteStorageError(
                    f"{method} {url} unreachable: {exc}"
                ) from exc
            continue
        # a response of ANY status proves the endpoint is alive: HTTP
        # errors are the server talking, not the dependency being down
        breaker.record_success()
        if resp.status >= 400:
            detail = resp.read().decode("utf-8", "replace")[:500]
            if resp.isclosed() and not getattr(resp, "will_close", False):
                _return_conn(netloc, conn)
            else:
                conn.close()
            raise RemoteStorageError(
                f"{method} {url} → HTTP {resp.status}: {detail}",
                code=resp.status,
            )
        return _PooledResponse(resp, conn, netloc)
    raise AssertionError("unreachable")  # pragma: no cover


def _json(resp) -> dict:
    return json.loads(resp.read())


class RemoteEventStore(EventStore):
    """``EventStore`` over the storage server's /events routes."""

    def __init__(self, base_url: str, timeout: float = 60.0):
        # 60 s default mirrors the reference LEvents op timeout
        # (LEvents.scala:35).
        self._base = base_url.rstrip("/")
        self._timeout = timeout

    def _url(self, app_id: int, suffix: str = "") -> str:
        return f"{self._base}/events/{app_id}{suffix}"

    def init(self, app_id: int) -> bool:
        with _request(self._url(app_id, "/init"), "POST", b"{}",
                      self._timeout, idempotent=True) as r:
            return bool(_json(r)["ok"])

    def remove(self, app_id: int) -> bool:
        with _request(self._url(app_id, "/remove"), "POST", b"{}",
                      self._timeout, idempotent=True) as r:
            return bool(_json(r)["ok"])

    def insert(self, event: Event, app_id: int) -> str:
        body = json.dumps(event.to_json_dict()).encode()
        # An event that already carries its id (client-assigned, or
        # minted from an idempotencyKey upstream) is an UPSERT on the
        # server: replaying it lands on itself, so the POST may take the
        # one-shot stale-connection retry. Unkeyed inserts keep NO retry
        # — a replay would double-insert.
        with _request(
            self._url(app_id), "POST", body, self._timeout,
            idempotent=event.event_id is not None,
        ) as r:
            return _json(r)["eventId"]

    def get(self, event_id: str, app_id: int) -> Optional[Event]:
        try:
            with _request(self._url(app_id, f"/{event_id}"), timeout=self._timeout) as r:
                return Event.from_json_dict(_json(r))
        except RemoteStorageError as exc:
            if exc.code == 404:
                return None
            raise

    def delete(self, event_id: str, app_id: int) -> bool:
        with _request(
            self._url(app_id, f"/{event_id}"), "DELETE", timeout=self._timeout
        ) as r:
            return bool(_json(r)["found"])

    def find(
        self, app_id: int, filter: Optional[EventFilter] = None
    ) -> Iterator[Event]:
        body = self._filter_dict(filter or EventFilter())
        resp = _request(
            self._url(app_id, "/find"), "POST", json.dumps(body).encode(),
            self._timeout, idempotent=True,  # pure read
        )

        def iterate() -> Iterator[Event]:
            with resp:
                for line in resp:  # http.client decodes the chunked framing
                    line = line.strip()
                    if line:
                        yield Event.from_json_dict(json.loads(line))

        return iterate()

    def _filter_dict(self, flt: EventFilter) -> dict:
        return {
            "start_time": flt.start_time.isoformat() if flt.start_time else None,
            "until_time": flt.until_time.isoformat() if flt.until_time else None,
            "entity_type": flt.entity_type,
            "entity_id": flt.entity_id,
            "event_names": list(flt.event_names) if flt.event_names else None,
            "target_entity_type": flt.target_entity_type,
            "target_entity_id": flt.target_entity_id,
            "has_target_entity_type": flt.has_target_entity_type,
            "has_target_entity_id": flt.has_target_entity_id,
            "limit": flt.limit,
            "reversed": flt.reversed,
        }

    def scan_columnar(self, app_id: int, filter: Optional[EventFilter] = None):
        """Columnar fast path over the wire (same contract as
        ``SqliteEventStore.scan_columnar``); the server delegates to the
        backing store's native columnar scan."""
        import numpy as np

        body = json.dumps(self._filter_dict(filter or EventFilter())).encode()
        with _request(
            self._url(app_id, "/scan_columnar"), "POST", body,
            self._timeout, idempotent=True,  # pure read
        ) as r:
            cols = _json(r)
        cols["event_time_ms"] = np.asarray(cols["event_time_ms"], dtype=np.int64)
        return cols

    def write(self, events, app_id: int) -> None:
        body = json.dumps([e.to_json_dict() for e in events]).encode()
        with _request(self._url(app_id, "/batch"), "POST", body, self._timeout):
            pass

    def write_new(self, events, app_id: int) -> None:
        """Freshness contract forwarded to the server so the backing store
        can take its guaranteed-new batch path."""
        body = json.dumps([e.to_json_dict() for e in events]).encode()
        with _request(
            self._url(app_id, "/batch?fresh=1"), "POST", body, self._timeout
        ):
            pass


#: Pure-read metadata RPCs: pooled keep-alive + stale retry is safe for
#: these (re-reading is harmless). Mutations (gen_next, inserts, updates,
#: deletes) get no stale retry — gen_next retried twice burns a sequence
#: value, an insert retried twice duplicates a row. An explicit allowlist,
#: like METADATA_RPC_METHODS itself: a future method must be classified
#: deliberately, never by name pattern.
_READ_RPC_METHODS = frozenset(
    {
        "app_get",
        "app_get_by_name",
        "app_get_all",
        "access_key_get",
        "access_key_get_by_app",
        "manifest_get",
        "engine_instance_get",
        "engine_instance_get_all",
        "engine_instance_get_latest_completed",
        "evaluation_instance_get",
        "evaluation_instance_get_completed",
    }
)
assert _READ_RPC_METHODS <= METADATA_RPC_METHODS


class _RemoteRPC:
    """One metadata RPC method bound to a URL."""

    def __init__(self, base: str, method: str, timeout: float):
        self._base, self._method, self._timeout = base, method, timeout
        self._idempotent = method in _READ_RPC_METHODS

    def __call__(self, *args):
        body = json.dumps(
            {"method": self._method, "args": [encode(a) for a in args]}
        ).encode()
        with _request(f"{self._base}/metadata/rpc", "POST", body,
                      self._timeout, idempotent=self._idempotent) as r:
            return decode(_json(r)["result"])


class RemoteMetadataStore:
    """Duck-typed ``MetadataStore`` forwarding every DAO method over RPC.

    The method list is pinned server-side (``METADATA_RPC_METHODS``); here
    each becomes a bound callable, so call sites are oblivious to the wire.
    """

    def __init__(self, base_url: str, timeout: float = 60.0):
        base = base_url.rstrip("/")
        for method in METADATA_RPC_METHODS:
            setattr(self, method, _RemoteRPC(base, method, timeout))

    def close(self) -> None:
        pass


class RemoteModelStore(ModelStore):
    def __init__(self, base_url: str, timeout: float = 60.0):
        self._base = base_url.rstrip("/")
        self._timeout = timeout

    def insert(self, model: Model) -> None:
        # PUT-by-id is a natural upsert: replaying it is safe
        with _request(
            f"{self._base}/models/{model.id}", "PUT", model.models,
            self._timeout, idempotent=True,
        ):
            pass

    def get(self, id: str) -> Optional[Model]:
        try:
            with _request(f"{self._base}/models/{id}", timeout=self._timeout) as r:
                return Model(id=id, models=r.read())
        except RemoteStorageError as exc:
            if exc.code == 404:
                return None
            raise

    def delete(self, id: str) -> None:
        with _request(f"{self._base}/models/{id}", "DELETE", timeout=self._timeout):
            pass


def _base_url(conf: SourceConf) -> str:
    host = conf.get("host", "127.0.0.1")
    port = int(conf.get("port", DEFAULT_PORT))
    return f"http://{host}:{port}"


register_backend(
    BackendFamily(
        name="remote",
        events=lambda c: RemoteEventStore(_base_url(c)),
        metadata=lambda c: RemoteMetadataStore(_base_url(c)),
        models=lambda c: RemoteModelStore(_base_url(c)),
    )
)
