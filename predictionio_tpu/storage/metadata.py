"""Metadata records and DAOs.

Rebuild of the reference's metadata store surface
(``data/src/main/scala/io/prediction/data/storage/``): ``App``
(``Apps.scala:15-30``), ``AccessKey`` (``AccessKeys.scala:17-22``),
``EngineManifest`` (``EngineManifests.scala:20-31``), ``EngineInstance``
(``EngineInstances.scala:21-47``) and ``EvaluationInstance``
(``EvaluationInstances.scala:21-49``), each with a CRUD DAO. The reference
backs these with Elasticsearch documents; here they live in SQLite tables —
the metadata plane is a control plane and never touches the TPU.

All DAOs share one connection/lock, so a CLI process, an event server and a
training run can coexist against the same metadata file (the reference's
cross-JVM handshake through the shared store, SURVEY §1).
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import json
import os
import secrets
import sqlite3
import threading
from typing import Dict, List, Optional, Sequence

from .event import UTC, to_millis as _ms, utcnow

# EngineInstance / EvaluationInstance status values used by the workflow
# (CreateWorkflow.scala:245-253, CoreWorkflow.scala:77, Console.scala:742-780).
STATUS_INIT = "INIT"
STATUS_TRAINING = "TRAINING"
STATUS_COMPLETED = "COMPLETED"
STATUS_EVALUATING = "EVALUATING"
STATUS_EVALCOMPLETED = "EVALCOMPLETED"

# RolloutPlan lifecycle stages (docs/rollouts.md). SHADOW and CANARY are
# the in-flight stages a restarted query server resumes; the terminal
# stages are the durable outcome the fleet audits after the fact.
ROLLOUT_SHADOW = "SHADOW"
ROLLOUT_CANARY = "CANARY"
ROLLOUT_LIVE = "LIVE"
ROLLOUT_ROLLED_BACK = "ROLLED_BACK"
ROLLOUT_ABORTED = "ABORTED"
ROLLOUT_ACTIVE_STAGES = (ROLLOUT_SHADOW, ROLLOUT_CANARY)
ROLLOUT_TERMINAL_STAGES = (ROLLOUT_LIVE, ROLLOUT_ROLLED_BACK, ROLLOUT_ABORTED)


@dataclasses.dataclass(frozen=True)
class App:
    """``Apps.scala:15-30``."""

    id: int
    name: str
    description: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class AccessKey:
    """``AccessKeys.scala:17-22``; empty ``events`` allows all event names."""

    key: str
    appid: int
    events: Sequence[str] = ()


@dataclasses.dataclass(frozen=True)
class EngineManifest:
    """``EngineManifests.scala:20-35``."""

    id: str
    version: str
    name: str
    description: Optional[str] = None
    files: Sequence[str] = ()
    engine_factory: str = ""


@dataclasses.dataclass(frozen=True)
class EngineInstance:
    """Full record of one train/deploy run (``EngineInstances.scala:21-47``)."""

    id: str
    status: str
    start_time: _dt.datetime
    end_time: _dt.datetime
    engine_id: str
    engine_version: str
    engine_variant: str
    engine_factory: str
    batch: str = ""
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    data_source_params: str = ""
    preparator_params: str = ""
    algorithms_params: str = ""
    serving_params: str = ""


@dataclasses.dataclass(frozen=True)
class RolloutPlan:
    """Durable record of one staged deploy (``docs/rollouts.md``).

    The rollout plane's source of truth: a query server restarted
    mid-canary re-resolves the active plan for its engine tuple and
    resumes the same sticky split (``salt`` + ``percent`` are the whole
    routing function, so the assignment survives process death and the
    HA read-failover path). ``gates`` holds the resolved
    :class:`~predictionio_tpu.rollout.plan.GateConfig` values;
    ``history`` appends one ``{"stage", "atMs", "reason"}`` entry per
    transition — the audit trail the dashboard renders."""

    id: str
    stage: str
    engine_id: str
    engine_version: str
    engine_variant: str
    baseline_instance_id: str
    candidate_instance_id: str
    percent: float
    salt: str
    created_time: _dt.datetime
    updated_time: _dt.datetime
    gates: Dict[str, float] = dataclasses.field(default_factory=dict)
    history: List[dict] = dataclasses.field(default_factory=list)


@dataclasses.dataclass(frozen=True)
class EvaluationInstance:
    """Record of one evaluation run (``EvaluationInstances.scala:21-49``)."""

    id: str
    status: str
    start_time: _dt.datetime
    end_time: _dt.datetime
    evaluation_class: str = ""
    engine_params_generator_class: str = ""
    batch: str = ""
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    evaluator_results: str = ""
    evaluator_results_html: str = ""
    evaluator_results_json: str = ""


def _from_ms(ms: int) -> _dt.datetime:
    return _dt.datetime.fromtimestamp(ms / 1000.0, tz=UTC)


_SCHEMA = """
CREATE TABLE IF NOT EXISTS pio_apps (
  id INTEGER PRIMARY KEY AUTOINCREMENT, name TEXT UNIQUE NOT NULL,
  description TEXT);
CREATE TABLE IF NOT EXISTS pio_access_keys (
  key TEXT PRIMARY KEY, appid INTEGER NOT NULL, events TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS pio_engine_manifests (
  id TEXT NOT NULL, version TEXT NOT NULL, name TEXT NOT NULL,
  description TEXT, files TEXT NOT NULL, engine_factory TEXT NOT NULL,
  PRIMARY KEY (id, version));
CREATE TABLE IF NOT EXISTS pio_engine_instances (
  id TEXT PRIMARY KEY, status TEXT NOT NULL,
  start_time_ms INTEGER NOT NULL, end_time_ms INTEGER NOT NULL,
  engine_id TEXT NOT NULL, engine_version TEXT NOT NULL,
  engine_variant TEXT NOT NULL, engine_factory TEXT NOT NULL,
  batch TEXT NOT NULL DEFAULT '', env TEXT NOT NULL DEFAULT '{}',
  data_source_params TEXT NOT NULL DEFAULT '',
  preparator_params TEXT NOT NULL DEFAULT '',
  algorithms_params TEXT NOT NULL DEFAULT '',
  serving_params TEXT NOT NULL DEFAULT '');
CREATE TABLE IF NOT EXISTS pio_evaluation_instances (
  id TEXT PRIMARY KEY, status TEXT NOT NULL,
  start_time_ms INTEGER NOT NULL, end_time_ms INTEGER NOT NULL,
  evaluation_class TEXT NOT NULL DEFAULT '',
  engine_params_generator_class TEXT NOT NULL DEFAULT '',
  batch TEXT NOT NULL DEFAULT '', env TEXT NOT NULL DEFAULT '{}',
  evaluator_results TEXT NOT NULL DEFAULT '',
  evaluator_results_html TEXT NOT NULL DEFAULT '',
  evaluator_results_json TEXT NOT NULL DEFAULT '');
CREATE TABLE IF NOT EXISTS pio_sequences (
  name TEXT PRIMARY KEY, value INTEGER NOT NULL);
CREATE TABLE IF NOT EXISTS pio_rollout_plans (
  id TEXT PRIMARY KEY, stage TEXT NOT NULL,
  engine_id TEXT NOT NULL, engine_version TEXT NOT NULL,
  engine_variant TEXT NOT NULL,
  baseline_instance_id TEXT NOT NULL,
  candidate_instance_id TEXT NOT NULL,
  percent REAL NOT NULL, salt TEXT NOT NULL,
  created_ms INTEGER NOT NULL, updated_ms INTEGER NOT NULL,
  gates TEXT NOT NULL DEFAULT '{}',
  history TEXT NOT NULL DEFAULT '[]');
"""


class MetadataStore:
    """All metadata DAOs over one SQLite database."""

    def __init__(self, path: str = ":memory:"):
        self._path = path
        if path != ":memory:":
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._lock = threading.RLock()
        self._conn = sqlite3.connect(
            path, check_same_thread=False, timeout=30.0
        )
        with self._lock:
            if path != ":memory:":
                # WAL so a CLI, event server and training run can genuinely
                # coexist on one metadata file (readers don't block writers).
                self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA busy_timeout=30000")
            self._conn.executescript(_SCHEMA)
            self._conn.commit()

    def close(self) -> None:
        with self._lock:
            self._conn.close()

    # -- sequences (ESSequences analogue) ---------------------------------
    def gen_next(self, name: str) -> int:
        with self._lock:
            self._conn.execute(
                "INSERT INTO pio_sequences (name, value) VALUES (?, 0) "
                "ON CONFLICT(name) DO NOTHING",
                (name,),
            )
            self._conn.execute(
                "UPDATE pio_sequences SET value = value + 1 WHERE name = ?",
                (name,),
            )
            (value,) = self._conn.execute(
                "SELECT value FROM pio_sequences WHERE name = ?", (name,)
            ).fetchone()
            self._conn.commit()
            return int(value)

    def sequence_advance_to(self, name: str, value: int) -> None:
        """Idempotent replication helper: make ``gen_next(name)`` never
        re-issue a value ≤ ``value``. Replicas replay logged ``gen_next``
        results through this (``storage/changefeed.py``) so re-applying a
        log suffix cannot re-advance the counter."""
        with self._lock:
            self._conn.execute(
                "INSERT INTO pio_sequences (name, value) VALUES (?, ?) "
                "ON CONFLICT(name) DO UPDATE SET value = "
                "max(pio_sequences.value, excluded.value)",
                (name, int(value)),
            )
            self._conn.commit()

    # -- apps (Apps.scala DAO) --------------------------------------------
    def app_insert(self, app: App) -> Optional[int]:
        with self._lock:
            try:
                cur = self._conn.execute(
                    "INSERT INTO pio_apps (id, name, description) VALUES (?,?,?)",
                    (app.id if app.id else None, app.name, app.description),
                )
                self._conn.commit()
                return int(cur.lastrowid)
            except sqlite3.IntegrityError:
                return None

    def app_get(self, app_id: int) -> Optional[App]:
        with self._lock:
            row = self._conn.execute(
                "SELECT id, name, description FROM pio_apps WHERE id = ?",
                (app_id,),
            ).fetchone()
        return App(*row) if row else None

    def app_get_by_name(self, name: str) -> Optional[App]:
        with self._lock:
            row = self._conn.execute(
                "SELECT id, name, description FROM pio_apps WHERE name = ?",
                (name,),
            ).fetchone()
        return App(*row) if row else None

    def app_get_all(self) -> List[App]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT id, name, description FROM pio_apps ORDER BY id"
            ).fetchall()
        return [App(*r) for r in rows]

    def app_update(self, app: App) -> bool:
        with self._lock:
            cur = self._conn.execute(
                "UPDATE pio_apps SET name = ?, description = ? WHERE id = ?",
                (app.name, app.description, app.id),
            )
            self._conn.commit()
            return cur.rowcount > 0

    def app_delete(self, app_id: int) -> bool:
        with self._lock:
            cur = self._conn.execute(
                "DELETE FROM pio_apps WHERE id = ?", (app_id,)
            )
            self._conn.commit()
            return cur.rowcount > 0

    # -- access keys ------------------------------------------------------
    def access_key_insert(self, ak: AccessKey) -> Optional[str]:
        key = ak.key or secrets.token_urlsafe(48)
        with self._lock:
            try:
                self._conn.execute(
                    "INSERT INTO pio_access_keys (key, appid, events) "
                    "VALUES (?,?,?)",
                    (key, ak.appid, json.dumps(list(ak.events))),
                )
                self._conn.commit()
                return key
            except sqlite3.IntegrityError:
                return None

    def access_key_get(self, key: str) -> Optional[AccessKey]:
        with self._lock:
            row = self._conn.execute(
                "SELECT key, appid, events FROM pio_access_keys WHERE key = ?",
                (key,),
            ).fetchone()
        return (
            AccessKey(row[0], row[1], tuple(json.loads(row[2]))) if row else None
        )

    def access_key_get_by_app(self, app_id: int) -> List[AccessKey]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT key, appid, events FROM pio_access_keys "
                "WHERE appid = ?",
                (app_id,),
            ).fetchall()
        return [AccessKey(r[0], r[1], tuple(json.loads(r[2]))) for r in rows]

    def access_key_delete(self, key: str) -> bool:
        with self._lock:
            cur = self._conn.execute(
                "DELETE FROM pio_access_keys WHERE key = ?", (key,)
            )
            self._conn.commit()
            return cur.rowcount > 0

    # -- engine manifests --------------------------------------------------
    def manifest_update(self, m: EngineManifest, upsert: bool = True) -> bool:
        """Update a manifest; with ``upsert=False``, only overwrite an
        existing (id, version) row (``EngineManifests.update`` semantics)."""
        with self._lock:
            if not upsert:
                exists = self._conn.execute(
                    "SELECT 1 FROM pio_engine_manifests WHERE id=? AND version=?",
                    (m.id, m.version),
                ).fetchone()
                if not exists:
                    return False
            self._conn.execute(
                "INSERT OR REPLACE INTO pio_engine_manifests VALUES (?,?,?,?,?,?)",
                (
                    m.id,
                    m.version,
                    m.name,
                    m.description,
                    json.dumps(list(m.files)),
                    m.engine_factory,
                ),
            )
            self._conn.commit()
            return True

    def manifest_get(self, id: str, version: str) -> Optional[EngineManifest]:
        with self._lock:
            row = self._conn.execute(
                "SELECT * FROM pio_engine_manifests WHERE id=? AND version=?",
                (id, version),
            ).fetchone()
        if not row:
            return None
        return EngineManifest(
            id=row[0],
            version=row[1],
            name=row[2],
            description=row[3],
            files=tuple(json.loads(row[4])),
            engine_factory=row[5],
        )

    # -- engine instances --------------------------------------------------
    def engine_instance_insert(self, inst: EngineInstance) -> str:
        iid = inst.id or f"EI-{self.gen_next('engine_instances'):08d}"
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO pio_engine_instances "
                "VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
                (
                    iid,
                    inst.status,
                    _ms(inst.start_time),
                    _ms(inst.end_time),
                    inst.engine_id,
                    inst.engine_version,
                    inst.engine_variant,
                    inst.engine_factory,
                    inst.batch,
                    json.dumps(inst.env),
                    inst.data_source_params,
                    inst.preparator_params,
                    inst.algorithms_params,
                    inst.serving_params,
                ),
            )
            self._conn.commit()
        return iid

    def _row_to_engine_instance(self, row) -> EngineInstance:
        return EngineInstance(
            id=row[0],
            status=row[1],
            start_time=_from_ms(row[2]),
            end_time=_from_ms(row[3]),
            engine_id=row[4],
            engine_version=row[5],
            engine_variant=row[6],
            engine_factory=row[7],
            batch=row[8],
            env=json.loads(row[9]),
            data_source_params=row[10],
            preparator_params=row[11],
            algorithms_params=row[12],
            serving_params=row[13],
        )

    def engine_instance_get(self, id: str) -> Optional[EngineInstance]:
        with self._lock:
            row = self._conn.execute(
                "SELECT * FROM pio_engine_instances WHERE id = ?", (id,)
            ).fetchone()
        return self._row_to_engine_instance(row) if row else None

    def engine_instance_get_all(self) -> List[EngineInstance]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT * FROM pio_engine_instances ORDER BY start_time_ms"
            ).fetchall()
        return [self._row_to_engine_instance(r) for r in rows]

    def engine_instance_get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[EngineInstance]:
        """``getLatestCompleted`` — deploy picks this (``Console.scala:742``)."""
        with self._lock:
            row = self._conn.execute(
                "SELECT * FROM pio_engine_instances WHERE status = ? AND "
                "engine_id = ? AND engine_version = ? AND engine_variant = ? "
                "ORDER BY start_time_ms DESC LIMIT 1",
                (STATUS_COMPLETED, engine_id, engine_version, engine_variant),
            ).fetchone()
        return self._row_to_engine_instance(row) if row else None

    def engine_instance_update(self, inst: EngineInstance) -> bool:
        self.engine_instance_insert(inst)
        return True

    def engine_instance_delete(self, id: str) -> bool:
        with self._lock:
            cur = self._conn.execute(
                "DELETE FROM pio_engine_instances WHERE id = ?", (id,)
            )
            self._conn.commit()
            return cur.rowcount > 0

    # -- rollout plans (docs/rollouts.md) ----------------------------------
    def rollout_plan_upsert(self, plan: RolloutPlan) -> str:
        """Insert-or-replace one plan; mints ``RO-...`` ids for blank
        ones. Every state transition goes through here, so replication
        (``storage/changefeed.py``) ships each transition like any other
        metadata mutation.

        Ids are random, not sequential: a sequence counter does not
        replicate through the changefeed (replayed upserts carry their
        resolved id), so after a replica promotion a counter-minted id
        would collide with a replicated plan and ``INSERT OR REPLACE``
        would silently destroy its audit history. Ordering comes from
        ``updated_ms``, not the id."""
        pid = plan.id or f"RO-{secrets.token_hex(6)}"
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO pio_rollout_plans "
                "VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?)",
                (
                    pid,
                    plan.stage,
                    plan.engine_id,
                    plan.engine_version,
                    plan.engine_variant,
                    plan.baseline_instance_id,
                    plan.candidate_instance_id,
                    float(plan.percent),
                    plan.salt,
                    _ms(plan.created_time),
                    _ms(plan.updated_time),
                    json.dumps(plan.gates),
                    json.dumps(list(plan.history)),
                ),
            )
            self._conn.commit()
        return pid

    def _row_to_rollout_plan(self, row) -> RolloutPlan:
        return RolloutPlan(
            id=row[0],
            stage=row[1],
            engine_id=row[2],
            engine_version=row[3],
            engine_variant=row[4],
            baseline_instance_id=row[5],
            candidate_instance_id=row[6],
            percent=row[7],
            salt=row[8],
            created_time=_from_ms(row[9]),
            updated_time=_from_ms(row[10]),
            gates=json.loads(row[11]),
            history=json.loads(row[12]),
        )

    def rollout_plan_get(self, id: str) -> Optional[RolloutPlan]:
        with self._lock:
            row = self._conn.execute(
                "SELECT * FROM pio_rollout_plans WHERE id = ?", (id,)
            ).fetchone()
        return self._row_to_rollout_plan(row) if row else None

    def rollout_plan_get_all(self) -> List[RolloutPlan]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT * FROM pio_rollout_plans "
                "ORDER BY updated_ms DESC, id DESC"
            ).fetchall()
        return [self._row_to_rollout_plan(r) for r in rows]

    def rollout_plan_get_latest(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[RolloutPlan]:
        """Most recently updated plan for one engine tuple, any stage —
        how a restarting server learns a ROLLED_BACK candidate must not
        be implicitly redeployed as the latest-completed instance."""
        with self._lock:
            row = self._conn.execute(
                "SELECT * FROM pio_rollout_plans WHERE "
                "engine_id = ? AND engine_version = ? AND engine_variant = ? "
                "ORDER BY updated_ms DESC LIMIT 1",
                (engine_id, engine_version, engine_variant),
            ).fetchone()
        return self._row_to_rollout_plan(row) if row else None

    def rollout_plan_get_active(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[RolloutPlan]:
        """The in-flight (SHADOW/CANARY) plan for one engine tuple —
        what a restarted query server resumes. ``start`` refuses to open
        a second plan while one is active, so at most one row matches."""
        with self._lock:
            row = self._conn.execute(
                "SELECT * FROM pio_rollout_plans WHERE stage IN (?, ?) AND "
                "engine_id = ? AND engine_version = ? AND engine_variant = ? "
                "ORDER BY updated_ms DESC LIMIT 1",
                (
                    ROLLOUT_SHADOW,
                    ROLLOUT_CANARY,
                    engine_id,
                    engine_version,
                    engine_variant,
                ),
            ).fetchone()
        return self._row_to_rollout_plan(row) if row else None

    # -- evaluation instances ----------------------------------------------
    def evaluation_instance_insert(self, inst: EvaluationInstance) -> str:
        iid = inst.id or f"EVI-{self.gen_next('evaluation_instances'):08d}"
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO pio_evaluation_instances "
                "VALUES (?,?,?,?,?,?,?,?,?,?,?)",
                (
                    iid,
                    inst.status,
                    _ms(inst.start_time),
                    _ms(inst.end_time),
                    inst.evaluation_class,
                    inst.engine_params_generator_class,
                    inst.batch,
                    json.dumps(inst.env),
                    inst.evaluator_results,
                    inst.evaluator_results_html,
                    inst.evaluator_results_json,
                ),
            )
            self._conn.commit()
        return iid

    def _row_to_evaluation_instance(self, row) -> EvaluationInstance:
        return EvaluationInstance(
            id=row[0],
            status=row[1],
            start_time=_from_ms(row[2]),
            end_time=_from_ms(row[3]),
            evaluation_class=row[4],
            engine_params_generator_class=row[5],
            batch=row[6],
            env=json.loads(row[7]),
            evaluator_results=row[8],
            evaluator_results_html=row[9],
            evaluator_results_json=row[10],
        )

    def evaluation_instance_get(self, id: str) -> Optional[EvaluationInstance]:
        with self._lock:
            row = self._conn.execute(
                "SELECT * FROM pio_evaluation_instances WHERE id = ?", (id,)
            ).fetchone()
        return self._row_to_evaluation_instance(row) if row else None

    def evaluation_instance_get_completed(self) -> List[EvaluationInstance]:
        """Dashboard feed (``Dashboard.scala``): completed evals, newest first."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT * FROM pio_evaluation_instances WHERE status = ? "
                "ORDER BY start_time_ms DESC",
                (STATUS_EVALCOMPLETED,),
            ).fetchall()
        return [self._row_to_evaluation_instance(r) for r in rows]

    def evaluation_instance_update(self, inst: EvaluationInstance) -> bool:
        self.evaluation_instance_insert(inst)
        return True


def new_engine_instance(
    engine_id: str,
    engine_version: str,
    engine_variant: str,
    engine_factory: str,
    batch: str = "",
    env: Optional[Dict[str, str]] = None,
    data_source_params: str = "",
    preparator_params: str = "",
    algorithms_params: str = "",
    serving_params: str = "",
) -> EngineInstance:
    now = utcnow()
    return EngineInstance(
        id="",
        status=STATUS_INIT,
        start_time=now,
        end_time=now,
        engine_id=engine_id,
        engine_version=engine_version,
        engine_variant=engine_variant,
        engine_factory=engine_factory,
        batch=batch,
        env=env or {},
        data_source_params=data_source_params,
        preparator_params=preparator_params,
        algorithms_params=algorithms_params,
        serving_params=serving_params,
    )
